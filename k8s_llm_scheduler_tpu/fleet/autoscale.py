"""SLO-burn-driven elastic fleet autoscaler (ROADMAP item 4).

The serving plane survives nine chaos regimes and self-improves its
policy, but through PR 11 it still "runs at N replicas" instead of
"serving the traffic": a diurnal 10x swing either burns money at peak
provisioning or burns the SLO budget. Every control input already
exists — the SLO engine's fast/slow burn windows (observability/slo.py),
the profiler's `queue_stall` segment (admission starvation, the
SARATHI-style pressure signal), merged fleet percentiles
(observability/fleetview.FleetAggregator), and pool occupancy
(fleet/pools.DisaggregatedBackend). This module closes the loop.

Control shape — a DEADBAND loop, robustness first:

- **pressure** is the max of normalized demand signals: queue depth per
  replica against the per-replica target, the SLO burn (only when BOTH
  windows exceed 1x — the multi-window discipline that keeps a blip
  from scaling the fleet), decide-p99 against an optional latency
  target, and profiler queue_stall beyond its budget. Max, not sum: any
  single starved dimension is a real capacity shortfall, and summing
  would let three healthy signals dilute one burning one.
- **hysteresis band**: no action while pressure sits inside
  [down_threshold, up_threshold]. Desired size re-targets
  `target_utilization` (below the up threshold), so the system lands
  INSIDE the band after a scale event and is stable there — flapping
  load at the threshold cannot produce one event per oscillation.
- **per-direction cooldowns**: scale-up needs `up_cooldown_s` since the
  last scale-up; scale-down needs `down_cooldown_s` since the last
  scale event of EITHER direction (an up immediately followed by a
  down is the thrash signature; the asymmetry keeps emergency up-scales
  fast while down-scales stay deliberate).
- **max-step clamp + [min, max] replica clamp**, and one scale
  OPERATION per tick regardless of the clamp — joins and drains are
  staggered (rollout/-style sequencing), so no wave observes a
  membership cliff.
- **health-gated join with rollback**: a new replica is admitted only
  after the dial/prewarm probe passes AND it claims its first lease
  (Fleet.start_join/complete_join); a join that fails or stalls past
  `join_budget_ticks` rolls back completely (abort_join), retries are
  BOUNDED (`max_join_retries`) with a tick-counted backoff, and the
  retry budget re-arms once the pressure that wanted the replica has
  dropped back to or below the up threshold.
- **drain-before-release scale-down**: removal rides
  Fleet.remove_replica — in-flight decisions complete their binds
  before leases release, survivors' fair-share claims converge on the
  freed shards (proven under chaos since PR 8), sockets tear down last.

The second output is the prefill<->decode POOL SPLIT
(fleet/pools.DisaggregatedBackend.set_split): when admission occupancy
dominates decode occupancy past its own deadband, members move to the
prefill pool (and back), on a separate cooldown.

Everything here runs on an INJECTED clock and is tick-driven — no
sleeps, no wall-time judgments — so the chaos harness drives the whole
loop in virtual wave time and byte-replays it (graftlint `resilience`
family clean by construction).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Any, Callable

from k8s_llm_scheduler_tpu.fleet.frontend import Fleet, JoinError, PendingJoin

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The control loop's knobs (config.yaml `autoscale` block)."""

    min_replicas: int = 1
    max_replicas: int = 8
    # demand normalization: work units (queued decisions) one replica
    # serves per tick at target utilization
    target_per_replica: float = 8.0
    # post-scale utilization the desired size re-targets — must sit
    # INSIDE the deadband or scale events would not converge
    target_utilization: float = 0.75
    up_threshold: float = 1.0
    down_threshold: float = 0.5
    max_step: int = 2
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    # health gate: ticks a pending join may wait for its first lease
    # claim before rollback, backoff ticks between attempts, and the
    # bounded retry budget (re-armed when pressure leaves the band)
    join_budget_ticks: int = 8
    join_backoff_ticks: int = 4
    max_join_retries: int = 3
    # optional latency pressure: decide p99 (merged fleet buckets)
    # against this target; None disables the term
    latency_target_ms: float | None = None
    # queue_stall fraction of wave wall time above which admission
    # counts as starved (profiler segment; SARATHI pressure)
    stall_budget: float = 0.25
    # prefill<->decode pool split control (None pools backend disables)
    split_enabled: bool = True
    split_cooldown_s: float = 60.0

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not self.down_threshold < self.target_utilization <= self.up_threshold:
            raise ValueError(
                "need down_threshold < target_utilization <= up_threshold "
                f"(got {self.down_threshold} / {self.target_utilization} / "
                f"{self.up_threshold}) — the desired size must land inside "
                "the deadband or scale events cannot converge"
            )
        if self.max_step < 1:
            raise ValueError("max_step must be >= 1")

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "AutoscaleConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known - {"enabled", "tick_interval_s"}
        if unknown:
            raise ValueError(
                f"autoscale config: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class AutoscaleSignals:
    """One tick's control inputs, already reduced to scalars."""

    queue_depth: float = 0.0        # decisions waiting (admission queue)
    slo_fast_burn: float = 0.0      # max fast-window burn across objectives
    slo_slow_burn: float = 0.0
    decide_p99_ms: float | None = None   # merged fleet percentile
    bind_p99_ms: float | None = None
    queue_stall_frac: float = 0.0   # profiler segment fraction
    prefill_occupancy: float = 0.0  # mean in-flight per pool member
    decode_occupancy: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class AutoscalePolicy:
    """The PURE decision function: (n, signals) -> pressure -> desired
    size. No clocks, no side effects — unit-testable arithmetic; the
    controller owns cooldowns and sequencing."""

    def __init__(self, cfg: AutoscaleConfig) -> None:
        self.cfg = cfg

    def pressure(self, n_replicas: int, sig: AutoscaleSignals) -> float:
        cfg = self.cfg
        n = max(1, n_replicas)
        parts = [sig.queue_depth / (cfg.target_per_replica * n)]
        if sig.slo_fast_burn > 1.0 and sig.slo_slow_burn > 1.0:
            # both windows burning: the budget is genuinely draining —
            # the burn magnitude (bounded by the fast window) says how
            # underprovisioned we are
            parts.append(min(sig.slo_fast_burn, sig.slo_slow_burn))
        if cfg.latency_target_ms and sig.decide_p99_ms:
            parts.append(sig.decide_p99_ms / cfg.latency_target_ms)
        if sig.queue_stall_frac > cfg.stall_budget:
            # admission starvation past budget reads as proportional
            # overload (stall_frac 2x the budget ~ 2x pressure)
            parts.append(sig.queue_stall_frac / cfg.stall_budget)
        return max(parts)

    def desired(self, n_replicas: int, pressure: float) -> int:
        """Deadband + re-target + step clamp + [min, max] clamp."""
        cfg = self.cfg
        clamped_now = min(max(n_replicas, cfg.min_replicas), cfg.max_replicas)
        if cfg.down_threshold <= pressure <= cfg.up_threshold:
            return clamped_now  # hold (hysteresis band)
        want = math.ceil(
            n_replicas * pressure / cfg.target_utilization
        ) if pressure > 0 else cfg.min_replicas
        if want > n_replicas:
            want = min(want, n_replicas + cfg.max_step)
        else:
            want = max(want, n_replicas - cfg.max_step)
        return min(max(want, cfg.min_replicas), cfg.max_replicas)


class AutoscaleController:
    """The tick-driven closed loop over an elastic Fleet.

    `tick()` is the whole protocol (deterministic given the injected
    clock and the signal providers): gather signals, progress any
    pending health-gated join, run the policy, apply AT MOST ONE scale
    operation, rebalance the pool split. Owners drive it — `cli run`
    from the SLO ticker cadence, the chaos harness once per wave in
    virtual time, the bench once per arrival wave.
    """

    def __init__(
        self,
        fleet: Fleet,
        cfg: AutoscaleConfig,
        *,
        queue_depth_fn: Callable[[], float] | None = None,
        slo_engine: Any = None,
        aggregator: Any = None,
        profiler: Any = None,
        pools: Any = None,
        clock: Callable[[], float] = time.monotonic,
        on_scale: Callable[[int, int, int], None] | None = None,
        event_limit: int = 4096,
    ) -> None:
        self.fleet = fleet
        self.cfg = cfg
        self.policy = AutoscalePolicy(cfg)
        self._queue_depth_fn = queue_depth_fn
        self._slo = slo_engine
        self._agg = aggregator
        self._profiler = profiler
        self._pools = pools
        self._clock = clock
        # invariant hook (chaos/invariants.py note_scale): fires after
        # every tick with (n_replicas, min, max)
        self.on_scale = on_scale
        self._event_limit = int(event_limit)
        self.tick_no = 0
        self.last_pressure = 0.0
        self._pending: PendingJoin | None = None
        self._join_retries = 0
        self._backoff_until_tick = 0
        self._last_up_t: float | None = None
        self._last_event_t: float | None = None
        self._last_split_t: float | None = None
        self.counters = {
            "ticks": 0,
            "scale_ups": 0,
            "scale_downs": 0,
            "holds": 0,
            "join_failures": 0,
            "split_changes": 0,
        }
        self.events: list[dict] = []

    # --------------------------------------------------------------- inputs
    def gather(self) -> AutoscaleSignals:
        sig = AutoscaleSignals()
        if self._queue_depth_fn is not None:
            sig.queue_depth = float(self._queue_depth_fn())
        if self._slo is not None:
            fast = slow = 0.0
            for detail in self._slo.snapshot().get("objectives", {}).values():
                if detail.get("fast"):
                    fast = max(fast, float(detail["fast"].get("burn", 0.0)))
                if detail.get("slow"):
                    slow = max(slow, float(detail["slow"].get("burn", 0.0)))
            sig.slo_fast_burn, sig.slo_slow_burn = fast, slow
        if self._agg is not None:
            decide = self._agg.fleet_percentiles("decide")
            if decide:
                sig.decide_p99_ms = float(decide["p99_ms"])
            bind = self._agg.fleet_percentiles("bind")
            if bind:
                sig.bind_p99_ms = float(bind["p99_ms"])
        if self._profiler is not None:
            sig.queue_stall_frac = float(
                self._profiler.gauges().get("queue_stall_frac", 0.0)
            )
        if self._pools is not None:
            occ = self._pools.occupancy()
            sig.prefill_occupancy = occ.get("prefill", 0.0)
            sig.decode_occupancy = occ.get("decode", 0.0)
        return sig

    # ------------------------------------------------------------- cooldowns
    def _up_allowed(self, now: float) -> bool:
        return (
            self._last_up_t is None
            or now - self._last_up_t >= self.cfg.up_cooldown_s
        )

    def _down_allowed(self, now: float) -> bool:
        return (
            self._last_event_t is None
            or now - self._last_event_t >= self.cfg.down_cooldown_s
        )

    def _note(self, action: str, n_from: int, n_to: int, pressure: float,
              detail: str = "") -> dict:
        event = {
            "tick": self.tick_no,
            "action": action,
            "n_from": n_from,
            "n_to": n_to,
            "pressure": round(pressure, 6),
        }
        if detail:
            event["detail"] = detail
        self.events.append(event)
        if len(self.events) > self._event_limit:
            del self.events[: len(self.events) - self._event_limit]
        return event

    # ----------------------------------------------------------------- tick
    async def tick(self) -> dict:
        """One control iteration; returns the tick record."""
        self.tick_no += 1
        self.counters["ticks"] += 1
        now = self._clock()
        sig = self.gather()
        n = self.fleet.n_live
        pressure = self.policy.pressure(n, sig)
        self.last_pressure = pressure

        record: dict
        if self._pending is not None:
            record = await self._progress_join(now, pressure)
        else:
            record = await self._steer(now, n, pressure)

        if pressure <= self.cfg.up_threshold:
            # the demand that wanted a replica has cleared (anywhere at
            # or below the up threshold — a trough counts): re-arm the
            # bounded join-retry budget for the NEXT excursion. Gating
            # this on the band interior would permanently lock out
            # scale-ups for a load that flaps heavy/light without ever
            # settling inside the band.
            self._join_retries = 0

        if self.on_scale is not None:
            self.on_scale(
                self.fleet.n_live, self.cfg.min_replicas,
                self.cfg.max_replicas,
            )
        self._steer_split(now)
        record["signals"] = sig.to_dict()
        return record

    async def _progress_join(self, now: float, pressure: float) -> dict:
        """Advance the pending health-gated join (staggered: nothing
        else scales while a join is open)."""
        join = self._pending
        assert join is not None
        n = self.fleet.n_live
        if not join.dead and await self.fleet.complete_join(join):
            self._pending = None
            self._join_retries = 0
            self._last_up_t = self._last_event_t = now
            self.counters["scale_ups"] += 1
            logger.info(
                "autoscale: %s admitted (gate complete, %d replicas)",
                join.replica.holder, n,
            )
            return self._note("join_admitted", n, n, pressure)
        if join.dead or join.ticks_waited >= self.cfg.join_budget_ticks:
            await self.fleet.abort_join(join)
            self._pending = None
            self._join_retries += 1
            self._backoff_until_tick = (
                self.tick_no + self.cfg.join_backoff_ticks
            )
            self.counters["join_failures"] += 1
            logger.warning(
                "autoscale: join of %s rolled back (%s; retry %d/%d)",
                join.replica.holder,
                "died mid-gate" if join.dead else "gate budget exhausted",
                self._join_retries, self.cfg.max_join_retries,
            )
            return self._note(
                "join_rolled_back", n, self.fleet.n_live, pressure,
                detail="dead" if join.dead else "budget",
            )
        return self._note("join_pending", n, n, pressure)

    async def _steer(self, now: float, n: int, pressure: float) -> dict:
        want = self.policy.desired(n, pressure)
        if want > n:
            if not self._up_allowed(now):
                return self._note("hold", n, n, pressure, detail="up_cooldown")
            if self._join_retries >= self.cfg.max_join_retries:
                return self._note(
                    "hold", n, n, pressure, detail="join_retries_exhausted"
                )
            if self.tick_no < self._backoff_until_tick:
                return self._note(
                    "hold", n, n, pressure, detail="join_backoff"
                )
            try:
                self._pending = await self.fleet.start_join()
            except JoinError as exc:
                self._join_retries += 1
                self._backoff_until_tick = (
                    self.tick_no + self.cfg.join_backoff_ticks
                )
                self.counters["join_failures"] += 1
                logger.warning("autoscale: join failed at start: %s", exc)
                return self._note(
                    "join_failed", n, n, pressure, detail=str(exc)
                )
            return self._note("join_started", n, n + 1, pressure)
        if want < n:
            if not self._down_allowed(now):
                return self._note(
                    "hold", n, n, pressure, detail="down_cooldown"
                )
            victim = self.fleet.pick_removal()
            await self.fleet.remove_replica(victim)
            self._last_event_t = now
            self.counters["scale_downs"] += 1
            logger.info(
                "autoscale: drained %s (%d -> %d replicas)",
                victim.holder, n, n - 1,
            )
            return self._note("scale_down", n, n - 1, pressure)
        self.counters["holds"] += 1
        return self._note("hold", n, n, pressure)

    # ------------------------------------------------------------ pool split
    def _steer_split(self, now: float) -> None:
        """Output #2: move pool members toward the occupancy ratio, on
        its own deadband + cooldown. Admission-heavy ticks grow the
        prefill pool; decode-heavy ticks shrink it back."""
        pools = self._pools
        if pools is None or not self.cfg.split_enabled:
            return
        if (
            self._last_split_t is not None
            and now - self._last_split_t < self.cfg.split_cooldown_s
        ):
            return
        occ = pools.occupancy()
        total_members = len(pools.prefill_pool) + len(pools.decode_pool)
        if total_members < 2 or not pools.decode_pool:
            return  # nothing to split (or already a pure prefill fleet)
        load = occ["prefill"] + occ["decode"]
        if load <= 0:
            return
        share = occ["prefill"] / load
        split_for_share = getattr(pools, "split_for_share", None)
        if split_for_share is not None:
            # Device-weighted on heterogeneous fleets (engine/sharded
            # slice geometry): the share buys whole tp groups' worth of
            # chips, and the split lands on a device-group boundary
            # instead of treating a tp=8 slice as one unit of capacity.
            want_prefill = min(
                max(1, int(split_for_share(share))), total_members - 1
            )
        else:
            want_prefill = min(
                max(1, round(total_members * share)), total_members - 1
            )
        if want_prefill == len(pools.prefill_pool):
            return
        split = pools.set_split(want_prefill)
        self._last_split_t = now
        self.counters["split_changes"] += 1
        logger.info("autoscale: pool split rebalanced to %s", split)

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The `autoscale` subtree of the fleet stats tree (rendered as
        llm_scheduler_autoscale_* gauges)."""
        return {
            **self.counters,
            "replicas": self.fleet.n_live,
            "min_replicas": self.cfg.min_replicas,
            "max_replicas": self.cfg.max_replicas,
            "pressure": round(self.last_pressure, 6),
            "join_pending": self._pending is not None,
            "join_retries": self._join_retries,
        }

    def scale_events(self) -> list[dict]:
        """Membership-changing events only (the chaos trace's
        deterministic scale record; holds and pending-gate ticks are
        cadence noise)."""
        return [
            e for e in self.events
            if e["action"] not in ("hold", "join_pending")
        ]


def from_config(
    fleet: Fleet, autoscale_cfg: dict[str, Any], **providers: Any
) -> AutoscaleController | None:
    """Build a controller from the config `autoscale` block (None when
    disabled)."""
    if not autoscale_cfg or not autoscale_cfg.get("enabled"):
        return None
    cfg = AutoscaleConfig.from_dict(autoscale_cfg)
    return AutoscaleController(fleet, cfg, **providers)
