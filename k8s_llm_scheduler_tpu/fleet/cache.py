"""Tiered decision cache: per-replica L1 in front of a fleet-shared L2.

At fleet scale the decision cache splits the same way CPU caches do:

- **L1** is private to one replica — small, contention-free (its lock is
  only ever taken by this replica's threads), and answering the common
  case: a burst's followers re-reading the leader's decision.
- **L2** is ONE DecisionCache object shared by every replica in the
  fleet (in-process fleets share it directly; a multi-process deployment
  would back this seam with a networked store). It is what makes a
  decision computed by replica A servable from replica B without a
  second model call — the fleet-wide single-flight economics.

Generation coherence is the part that must not be reinvented per tier:
`DecisionCache` already stamps every stored key with a policy
generation and `bump_generation()` makes older epochs unreachable
(rollout/hotswap.py). Here the **L2 is the generation authority**: a
hot weight swap anywhere in the fleet bumps L2 once, and every
replica's L1 catches up lazily on its next lookup (`set_generation` is
monotonic), so pre-swap decisions become unservable from BOTH tiers
without any cross-replica flush traffic. Straggler protection carries
through unchanged: DecisionClient captures `generation` before the
backend call and both tiers file the late decision under that old,
unreachable epoch.

The tiered cache exposes the exact DecisionCache surface DecisionClient
consumes (get/set/generation/bump_generation/stats/len/clear), so the
client stack is fleet-ready without modification.
"""

from __future__ import annotations

import threading
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.core.cache import DecisionCache, decision_cache_key
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec, SchedulingDecision


class TieredDecisionCache:
    """L1 (private) over L2 (shared, generation authority)."""

    def __init__(
        self,
        l2: DecisionCache,
        l1_size: int = 256,
        l1_ttl_s: float | None = None,
    ) -> None:
        self.l2 = l2
        self.l1 = DecisionCache(
            ttl_seconds=l2.ttl_seconds if l1_ttl_s is None else l1_ttl_s,
            max_size=l1_size,
        )
        self._tier_local = threading.local()
        self._lock = threading.Lock()
        self.l1_hits = 0
        self.l2_hits = 0
        self.misses = 0
        # Chaos seam (chaos/faults.py, seam "cache", kind "l2_down"):
        # None in production. While the shared tier is down, reads serve
        # from the private L1 only, writes are L1-only (nothing to share
        # with a dead tier), and generation sync pauses — the replica
        # keeps deciding on its last-known epoch and re-syncs on the
        # first lookup after recovery.
        self.fault_seam = None
        self.l2_unavailable = 0

    # ------------------------------------------------------------ coherence
    def _l2_up(self) -> bool:
        seam = self.fault_seam
        if seam is not None and seam.should("l2_down") is not None:
            with self._lock:
                self.l2_unavailable += 1
            return False
        return True

    def _sync(self, l2_up: bool | None = None) -> int:
        """Catch L1 up to the L2 epoch (monotonic; a no-op in the steady
        state). Called on every lookup/store so an L2 bump by ANOTHER
        replica invalidates this replica's L1 on its very next use.
        With the L2 unreachable, L1 keeps its last-known epoch — a
        bounded staleness window the first post-recovery sync closes.
        get/set pass their own `_l2_up()` reading so one operation
        consults the seam (and counts an outage) exactly once."""
        if l2_up is None:
            l2_up = self._l2_up()
        if not l2_up:
            return self.l1.generation
        return self.l1.set_generation(self.l2.generation)

    @property
    def generation(self) -> int:
        """The fleet epoch (L2's). DecisionClient captures this before
        the backend call, exactly as with a flat cache."""
        return self._sync()

    def bump_generation(self) -> int:
        """Hot swap: bump the shared epoch once; both tiers' older
        entries become unreachable (L1 via the sync that follows)."""
        gen = self.l2.bump_generation()
        self.l1.set_generation(gen)
        return gen

    # --------------------------------------------------------------- lookup
    def get(
        self,
        pod: PodSpec,
        nodes: Sequence[NodeMetrics],
        key: str | None = None,
    ) -> SchedulingDecision | None:
        if key is None:
            key = decision_cache_key(pod, nodes)
        l2_up = self._l2_up()
        self._sync(l2_up)
        decision = self.l1.get(pod, nodes, key=key)
        if decision is not None:
            with self._lock:
                self.l1_hits += 1
            self._tier_local.value = "l1_hit"
            return decision
        if not l2_up:
            with self._lock:
                self.misses += 1
            self._tier_local.value = "l2_down"
            return None
        decision = self.l2.get(pod, nodes, key=key)
        if decision is not None:
            # promote: the next lookup on this replica is an L1 hit and
            # never touches the shared tier's lock again
            self.l1.set(pod, nodes, decision, key=key)
            with self._lock:
                self.l2_hits += 1
            self._tier_local.value = "l2_hit"
            return decision
        with self._lock:
            self.misses += 1
        self._tier_local.value = "miss"
        return None

    def set(
        self,
        pod: PodSpec,
        nodes: Sequence[NodeMetrics],
        decision: SchedulingDecision,
        key: str | None = None,
        generation: int | None = None,
    ) -> None:
        """Write-through: the shared tier gets every decision (that is
        what makes it fleet-shared), the private tier keeps its copy hot.
        `generation` semantics are DecisionCache's: the epoch the
        decision was computed under, so post-swap stragglers file under
        their (unreachable) compute epoch in BOTH tiers."""
        if decision.fallback_needed:
            return
        if key is None:
            key = decision_cache_key(pod, nodes)
        l2_up = self._l2_up()
        self._sync(l2_up)
        self.l1.set(pod, nodes, decision, key=key, generation=generation)
        if l2_up:
            self.l2.set(pod, nodes, decision, key=key, generation=generation)

    # ---------------------------------------------------------- bookkeeping
    @property
    def last_tier(self) -> str | None:
        """This thread's last lookup outcome: l1_hit | l2_hit | miss —
        the flight recorder's cache_tier attribute."""
        return getattr(self._tier_local, "value", None)

    def clear(self) -> None:
        """Clears the PRIVATE tier only: the shared L2 belongs to the
        fleet, and one replica resetting everyone's cache is exactly the
        kind of cross-replica blast radius the tiering prevents."""
        self.l1.clear()

    def __len__(self) -> int:
        return len(self.l1)

    @property
    def ttl_seconds(self) -> float:
        return self.l2.ttl_seconds

    def stats(self) -> dict:
        with self._lock:
            tiers = {
                "l1_hits": self.l1_hits,
                "l2_hits": self.l2_hits,
                "misses": self.misses,
            }
            if self.l2_unavailable:
                tiers["l2_unavailable"] = self.l2_unavailable
        return {
            **tiers,
            "generation": self.l2.generation,
            "l1": self.l1.stats(),
            "l2": self.l2.stats(),
            # flat-cache compatibility for dashboards reading cache.hits:
            # a hit is a hit in either tier
            "size": len(self.l1),
            "hits": tiers["l1_hits"] + tiers["l2_hits"],
        }
