"""Fleet frontend: N sharded scheduler replicas over one cluster.

This is the composition layer the rest of `fleet/` exists for. Each
`FleetReplica` is a complete serving stack — Scheduler loop,
DecisionClient with a TieredDecisionCache (private L1 over the fleet's
shared L2), its own DecisionBackend — whose watch space is filtered to
the shards its LeaseManager currently holds. The `Fleet` object wires
replicas to the shared pieces (LeaseStore, L2, cluster) and runs them
as tasks on one event loop: the honest in-process twin of a
one-process-per-replica deployment, and the shape `bench.py --preset
fleet` and the failover tests drive.

Correctness story for failover (the part that must be exact):

1. a pod's shard never changes (hash of namespace/name);
2. each replica's watch filter drops pods of shards it does not hold —
   at most one replica SCHEDULES a pod at a time;
3. the fenced binder re-checks shard ownership against the lease
   manager at bind time, so a decision computed under a lease that
   expired mid-flight is discarded, not bound;
4. the cluster is the source of truth: bind of an already-bound pod
   fails at the apiserver (and at cluster/fake.py), so even a fencing
   race cannot double-bind — it can only waste one bind attempt;
5. when a replica gains a shard (initial claim or failover), it
   re-lists the cluster's still-pending pods for that shard and
   schedules them — pods the dead replica already bound are no longer
   pending, so the rebind pass is naturally exactly-once.
"""

from __future__ import annotations

import asyncio
import logging
from collections.abc import Sequence
from typing import Any, Callable

from k8s_llm_scheduler_tpu.cluster.interface import Binder, ClusterState, RawPod
from k8s_llm_scheduler_tpu.core.breaker import CircuitBreaker
from k8s_llm_scheduler_tpu.core.cache import DecisionCache
from k8s_llm_scheduler_tpu.fleet.cache import TieredDecisionCache
from k8s_llm_scheduler_tpu.fleet.lease import (
    LeaseManager,
    LeaseStore,
    assign_initial,
    shard_of,
)
from k8s_llm_scheduler_tpu.sched.client import DecisionClient
from k8s_llm_scheduler_tpu.sched.loop import Scheduler

logger = logging.getLogger(__name__)


class _ShardView:
    """ClusterState filtered to one replica's live shard set. Node reads
    pass through untouched (every replica needs the full snapshot — the
    decision prompt is cluster-wide); only the pending-pod stream is
    partitioned."""

    def __init__(
        self, inner: ClusterState, owns: Callable[[int], bool],
        n_shards: int,
    ) -> None:
        self._inner = inner
        self._owns = owns
        self._n_shards = n_shards

    def get_node_metrics(self):
        return self._inner.get_node_metrics()

    async def watch_pending_pods(self, scheduler_name: str):
        async for raw in self._inner.watch_pending_pods(scheduler_name):
            if self._owns(shard_of(raw.namespace, raw.name, self._n_shards)):
                yield raw
            # else: not ours — the shard's holder sees its own copy of
            # the event; an UNHELD shard's pods are picked up by the
            # rebind pass when some replica claims the shard


class _FencedBinder:
    """Bind-time lease fencing (correctness point 3 above).

    Two checks, cheapest first: the replica's LOCAL lease view (`owns` —
    lock-free set membership, catches the common case where the manager
    already processed a loss), then the STORE's fencing token (`verify`,
    backed by LeaseStore.check_fence with this replica's believed epoch).
    The store check is what makes the fence exact: a replica partitioned
    from the store keeps BELIEVING it holds its shards (its local view
    cannot learn otherwise), and before this check it would keep binding
    them while a survivor — which already claimed the shards under a new
    epoch — binds them too; the cluster's 409 made that a wasted bind and
    a nondeterministic winner. With the store check, a stale or
    unverifiable fencing token fails CLOSED: the bind is refused, the pod
    stays pending, and the shard's live holder (per the store) is the
    only replica that can land it. Cost: one store read per bind (a lock
    acquisition in-process; the apiserver Lease read a k8s-backed store
    would do)."""

    def __init__(
        self, inner: Binder, owns: Callable[[int], bool], n_shards: int,
        on_fenced: Callable[[], None] | None = None,
        verify: Callable[[int], bool] | None = None,
    ) -> None:
        self._inner = inner
        self._owns = owns
        self._n_shards = n_shards
        self._on_fenced = on_fenced
        self._verify = verify
        # preserve the loop's inline-bind fast path for in-memory binders
        self.bind_is_nonblocking = getattr(inner, "bind_is_nonblocking", False)

    def bind_pod_to_node(
        self, pod_name: str, namespace: str, node_name: str
    ) -> bool:
        shard = shard_of(namespace, pod_name, self._n_shards)
        fenced = not self._owns(shard)
        if not fenced and self._verify is not None:
            fenced = not self._verify(shard)
        if fenced:
            logger.warning(
                "fenced bind dropped: %s/%s -> %s (lease no longer held)",
                namespace, pod_name, node_name,
            )
            if self._on_fenced is not None:
                self._on_fenced()
            return False
        return self._inner.bind_pod_to_node(pod_name, namespace, node_name)


class FleetReplica:
    """One sharded scheduler replica (see module docstring)."""

    def __init__(
        self,
        replica_id: int,
        *,
        cluster: ClusterState,
        binder: Binder,
        backend: Any,
        store: LeaseStore,
        l2: DecisionCache,
        scheduler_name: str,
        l1_size: int = 256,
        renew_interval_s: float = 1.5,
        max_concurrency: int = 64,
        snapshot_ttl_s: float = 1.0,
        list_pending: Callable[[], Sequence[RawPod]] | None = None,
        journal: Any = None,
    ) -> None:
        self.replica_id = replica_id
        self.holder = f"replica-{replica_id}"
        self.backend = backend  # kept for teardown on elastic removal
        self._list_pending = list_pending
        self._loop: asyncio.AbstractEventLoop | None = None
        self.fenced_binds = 0
        self.manager = LeaseManager(
            store, self.holder,
            renew_interval_s=renew_interval_s,
            on_gain=self._on_gain,
        )
        self.cache = TieredDecisionCache(l2, l1_size=l1_size)
        self.client = DecisionClient(
            backend,
            cache=self.cache,
            breaker=CircuitBreaker(),
            retry_delay=0.05,
        )
        n_shards = store.n_shards
        # Durable decision journal (sched/journal.py): the bind chain
        # becomes fence(journal(binder)) — INSIDE the fence, so a
        # fenced-off bind never creates a recovery obligation — and the
        # breaker journals its trips so a restart restores OPEN with its
        # remaining cooldown. None (the default) costs nothing.
        self.journal = journal
        self._journaled_binder = None
        if journal is not None:
            from k8s_llm_scheduler_tpu.sched.recovery import JournaledBinder

            self._journaled_binder = JournaledBinder(
                binder, journal,
                shard_fn=lambda ns, name: shard_of(ns, name, n_shards),
                epoch_fn=self.manager.epoch_of,
            )
            binder = self._journaled_binder
            self.client.breaker.journal_sink = journal.record_breaker
        self.scheduler = Scheduler(
            _ShardView(cluster, self.manager.owns, n_shards),
            _FencedBinder(
                binder, self.manager.owns, n_shards, self._note_fenced,
                verify=self._store_fence,
            ),
            self.client,
            scheduler_name=scheduler_name,
            max_concurrency=max_concurrency,
            snapshot_ttl_s=snapshot_ttl_s,
            prefix_prewarm_s=0.0,  # the fleet router owns prewarm policy
        )
        # flight-recorder shard attribution (sched/loop stamps this on
        # every decision trace this replica records)
        self.scheduler.shard_fn = (
            lambda ns, name: shard_of(ns, name, n_shards)
        )
        self._task: asyncio.Task | None = None

    def _note_fenced(self) -> None:
        self.fenced_binds += 1  # GIL-atomic int bump; stats-only

    def _store_fence(self, shard: int) -> bool:
        """Store-side fencing-token verification for _FencedBinder: this
        replica's believed epoch must still be THE live lease. Any store
        failure (partition, apiserver outage) fails CLOSED — a bind we
        cannot verify is a bind we do not land."""
        epoch = self.manager.epoch_of(shard)
        if epoch is None:
            return False
        try:
            return self.manager.store.check_fence(shard, self.holder, epoch)
        except Exception:
            logger.warning(
                "%s: lease store unreachable at bind time for shard %d; "
                "failing closed", self.holder, shard,
            )
            return False

    # ------------------------------------------------------------- lifecycle
    async def start(self, lease_thread: bool = True) -> None:
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.create_task(self.scheduler.run())
        if lease_thread:
            self.manager.start()

    async def stop(self, release_leases: bool = True) -> None:
        """Clean shutdown (releases leases) or simulated crash
        (`release_leases=False`: leases linger until TTL — failover
        tests kill replicas this way). Leases are held until the
        scheduler has drained: releasing first would fence our own
        in-flight binds and report them as failed."""
        self.scheduler.stop()
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=30)
            except asyncio.TimeoutError:
                self._task.cancel()
            self._task = None
        self.manager.stop(release=release_leases)

    # ------------------------------------------------------------- recovery
    async def recover(self, pod_lookup) -> dict:
        """Crash-restart recovery (sched/recovery.py), run after a cold
        rebuild and BEFORE start(): tick the lease manager once so the
        fenced binder answers for our shards again (an unexpired own
        lease renews at the SAME epoch; an expired one re-acquires under
        a bumped epoch — either way the completion binds below run under
        a live fence), then replay-reconcile every open journal
        lifecycle against the cluster and restore the breaker.
        `pod_lookup(ns, name) -> ("bound", node) | ("pending", None) |
        ("gone", None)` is the cluster-truth probe (cluster/kube.py
        lookup_pod_node; cluster/fake.py get_pod)."""
        if self.journal is None:
            return {}
        from k8s_llm_scheduler_tpu.sched import recovery as recovery_mod

        self.manager.tick()
        crash_seam = getattr(self._journaled_binder, "crash_seam", None)
        report = await asyncio.to_thread(
            recovery_mod.recover,
            self.journal,
            pod_lookup=pod_lookup,
            binder=self.scheduler.binder,
            breaker=self.client.breaker,
            crash_seam=crash_seam,
        )
        return report.to_dict()

    # -------------------------------------------------------------- rebind
    def _on_gain(self, shards: frozenset[int]) -> None:
        """Lease-manager callback (manager tick thread OR the event loop
        in manual-tick tests): schedule a rebind scan for the gained
        shards on the replica's loop."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            task = asyncio.ensure_future(self._rebind(shards))
            self.scheduler._tasks.add(task)
            task.add_done_callback(self.scheduler._tasks.discard)
        else:
            asyncio.run_coroutine_threadsafe(self._rebind(shards), loop)

    async def _rebind(self, shards: frozenset[int]) -> None:
        """Re-list still-pending pods of the gained shards and schedule
        them (correctness point 5). Without a lister (live KubeCluster:
        the watch's periodic re-list re-delivers pending pods anyway)
        this is a no-op and convergence rides the watch."""
        if self._list_pending is None:
            return
        try:
            pending = await asyncio.to_thread(self._list_pending)
        except Exception:
            logger.exception("rebind re-list failed (%s)", self.holder)
            return
        n_shards = self.manager.store.n_shards
        todo = [
            raw for raw in pending
            if shard_of(raw.namespace, raw.name, n_shards) in shards
        ]
        if not todo:
            return
        logger.info(
            "%s: rebinding %d pending pod(s) from gained shards %s",
            self.holder, len(todo), sorted(shards),
        )
        await asyncio.gather(
            *(self.scheduler.schedule_pod(raw) for raw in todo),
            return_exceptions=True,
        )

    def get_stats(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "owned_shards": sorted(self.manager.owned()),
            "fenced_binds": self.fenced_binds,
            "lease": self.manager.stats(),
            **self.scheduler.get_stats(),
        }

    def telemetry(
        self, since_seq: int = 0, recorder: Any = None,
        sampler: Any = None, **caps: Any,
    ) -> dict:
        """This replica's pullable telemetry payload (observability/
        fleetview.build_telemetry shape): its stats tree — per-replica
        phase histograms ride along as bucket dicts — plus an optional
        flight-recorder slice. In-process fleets share ONE process-global
        flight recorder, so `recorder` defaults to None here and
        Fleet.aggregator() attaches the shared ring to exactly one
        source; a one-process-per-replica deployment passes its own."""
        from k8s_llm_scheduler_tpu.observability import fleetview

        return fleetview.build_telemetry(
            self.get_stats(), recorder, sampler, since_seq=since_seq, **caps
        )


class JoinError(RuntimeError):
    """A scale-up health gate failed (backend construction, the dial/
    prewarm probe, or a chaos-injected mid-join death). The join is
    rolled back by the caller; no partially-joined replica serves."""


class PendingJoin:
    """One in-flight scale-up: the replica is constructed, probed, and
    running, but not ADMITTED until its health gate completes — dial +
    prewarm probe already passed (start_join), first lease claim still
    pending (complete_join, driven by lease ticks). The controller holds
    this across ticks so the gate never blocks a control loop."""

    __slots__ = ("replica", "ticks_waited", "dead")

    def __init__(self, replica: FleetReplica) -> None:
        self.replica = replica
        self.ticks_waited = 0
        self.dead = False  # chaos: died mid-gate (never heartbeats)


class Fleet:
    """N replicas + the shared pieces, run on the current event loop.

    ELASTIC since the autoscale round: `start_join`/`complete_join`/
    `abort_join` grow the member set one health-gated replica at a time,
    and `remove_replica` shrinks it through the drain-before-release
    ordering FleetReplica.stop() already guarantees (in-flight decisions
    complete their binds BEFORE leases release — the PR 6 stop-ordering
    fix, now on the scale-down path). Scale events are staggered by
    construction: one join or one drain at a time, and removal below
    min 1 replica is refused, so no wave ever observes zero capacity."""

    def __init__(
        self,
        cluster: ClusterState,
        binder: Binder,
        backend_factory: Callable[[int], Any],
        *,
        n_replicas: int,
        n_shards: int | None = None,
        scheduler_name: str = "ai-llama-scheduler",
        lease_ttl_s: float = 5.0,
        renew_interval_s: float = 1.5,
        l1_size: int = 256,
        l2_size: int = 4096,
        l2_ttl_s: float = 300.0,
        max_concurrency: int = 64,
        snapshot_ttl_s: float = 1.0,
        clock=None,
        list_pending: Callable[[], Sequence[RawPod]] | None = None,
        store: LeaseStore | None = None,
        kvplane=None,
    ) -> None:
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if n_shards is None:
            # enough shards that failover redistributes in pieces, few
            # enough that the per-shard lease traffic stays trivial
            n_shards = max(2 * n_replicas, 8)
        self.n_shards = n_shards
        kwargs = {} if clock is None else {"clock": clock}
        if store is not None:
            # pluggable backend (durability.lease_store_path wires a
            # FileLeaseStore here): the caller's store must already be
            # sized for this fleet's shard space
            if store.n_shards != n_shards:
                raise ValueError(
                    f"injected lease store has {store.n_shards} shards, "
                    f"fleet wants {n_shards}"
                )
            self.store = store
        else:
            self.store = LeaseStore(n_shards, ttl_s=lease_ttl_s, **kwargs)
        self.l2 = DecisionCache(ttl_seconds=l2_ttl_s, max_size=l2_size)
        # Shared prefix-KV plane (fleet/kvplane/KVPlaneStore), one per
        # fleet: replicas whose backends can pin prefixes join it in
        # _make_replica, so ONE replica's snapshot prefill serves the
        # fleet. None = every replica prefills its own pins.
        self.kvplane = kvplane
        self._backend_factory = backend_factory
        self._mk = dict(
            cluster=cluster,
            binder=binder,
            scheduler_name=scheduler_name,
            l1_size=l1_size,
            renew_interval_s=renew_interval_s,
            max_concurrency=max_concurrency,
            snapshot_ttl_s=snapshot_ttl_s,
            list_pending=list_pending,
        )
        self._lease_threads = True  # recorded by start(); joins follow it
        # Chaos seam (chaos/faults.py, seam "scale"): None in production.
        # Interpreted at the join health gate: `join_fail` kills a
        # joining replica either at the dial/prewarm probe
        # (phase="dial") or silently mid-gate (phase="claim" — the
        # replica never heartbeats, so it never claims and the gate
        # times out into the rollback path).
        self.fault_seam = None
        # observation hook: called with a JOINING replica after its
        # probe passes and BEFORE its scheduler starts (it owns no
        # shards yet, so nothing can slip past the wrap) — the chaos
        # harness wraps binder/cache with the invariant monitor here,
        # the bench attaches its bind taps. None in production.
        self.on_replica_start: Callable[[FleetReplica], None] | None = None
        self.scale_counters = {
            "joins_started": 0,
            "joins_completed": 0,
            "joins_failed": 0,
            "removals": 0,
        }
        self.replicas = [self._make_replica(i) for i in range(n_replicas)]
        self._next_id = n_replicas

    def _make_replica(self, replica_id: int) -> FleetReplica:
        backend = self._backend_factory(replica_id)
        if self.kvplane is not None and hasattr(backend, "attach_kvplane"):
            backend.attach_kvplane(
                self.kvplane, replica=f"replica-{replica_id}"
            )
        return FleetReplica(
            replica_id,
            backend=backend,
            store=self.store,
            l2=self.l2,
            **self._mk,
        )

    async def start(self, lease_threads: bool = True) -> None:
        """Bootstrap ownership deterministically (every shard held
        before the first pod event), then start the replica loops. With
        `lease_threads=False` tests drive `tick_leases()` manually."""
        self._lease_threads = lease_threads
        assigned = assign_initial(
            self.store, [r.holder for r in self.replicas]
        )
        by_holder = {r.holder: r for r in self.replicas}
        for holder, leases in assigned.items():
            replica = by_holder[holder]
            for lease in leases:
                replica.manager.adopt(lease)
        for replica in self.replicas:
            await replica.start(lease_thread=lease_threads)

    def tick_leases(self) -> None:
        for replica in self.replicas:
            replica.manager.tick()

    async def stop(self) -> None:
        await asyncio.gather(*(r.stop() for r in self.replicas))

    async def kill_replica(self, index: int) -> None:
        """Simulated crash: the scheduler stops, leases are NOT
        released — failover happens via TTL expiry."""
        await self.replicas[index].stop(release_leases=False)

    # ----------------------------------------------------------- elasticity
    @property
    def n_live(self) -> int:
        return len(self.replicas)

    def _scale_seam_event(self, kind: str, key: str):
        seam = self.fault_seam
        return None if seam is None else seam.should(kind, key=key)

    async def start_join(self) -> PendingJoin:
        """Scale-up, phase 1 — construct + health-gate a new replica:

        1. the backend factory runs (a remote worker would be dialed
           here; a factory failure is a failed join, not a crash);
        2. the dial/prewarm probe: the backend must answer a cheap
           read (`health_probe()` when it has one, else `get_stats()`)
           — a replica that cannot answer must never enter the roster;
        3. the replica's scheduler starts and its lease manager begins
           heartbeating — it now counts toward everyone's fair share,
           so incumbents start shedding toward it.

        The replica is IN the roster from here (its watch filter owns
        nothing yet, so it schedules nothing), but the join is complete
        only when `complete_join` observes its first lease claim. Any
        failure raises JoinError after rolling the replica back out."""
        self.scale_counters["joins_started"] += 1
        replica_id = self._next_id
        holder = f"replica-{replica_id}"
        try:
            if self._scale_seam_event("join_fail", holder) is not None:
                raise JoinError(
                    f"{holder}: died mid-join (chaos join_fail)"
                )
            replica = self._make_replica(replica_id)
        except JoinError:
            self.scale_counters["joins_failed"] += 1
            raise
        except Exception as exc:
            self.scale_counters["joins_failed"] += 1
            raise JoinError(f"{holder}: backend factory failed: {exc}") from exc
        self._next_id = replica_id + 1
        join = PendingJoin(replica)
        try:
            probe = getattr(
                replica.backend, "health_probe", None
            ) or getattr(replica.backend, "get_stats", None)
            if probe is not None:
                await asyncio.to_thread(probe)
        except Exception as exc:
            self.scale_counters["joins_failed"] += 1
            self._close_backend(replica)
            raise JoinError(f"{holder}: dial/prewarm probe failed: {exc}") from exc
        # chaos gate_stall: the replica dies right AFTER the probe — it
        # never enters the roster, never heartbeats, never claims; the
        # dead flag tells the controller the death was OBSERVED, so it
        # rolls the join back on its next tick (a silent death nobody
        # observes is the separate budget-expiry path: a live joiner
        # that simply never claims)
        event = self._scale_seam_event("gate_stall", holder)
        join.dead = event is not None
        if join.dead:
            return join
        if self.on_replica_start is not None:
            self.on_replica_start(replica)
        self.replicas.append(replica)
        await replica.start(lease_thread=self._lease_threads)
        if not self._lease_threads:
            # manual-tick fleets: heartbeat immediately so the next
            # tick's fair-share census already counts the newcomer
            replica.manager.tick()
        return join

    def join_ready(self, join: PendingJoin) -> bool:
        """Has the joining replica claimed its first lease? (The last
        health-gate condition — callable from sync control loops.)"""
        return bool(join.replica.manager.owned())

    async def complete_join(self, join: PendingJoin) -> bool:
        """Scale-up, phase 2: admit the replica once it holds >= 1
        lease. Returns True when the gate is complete; the caller keeps
        driving ticks (and re-calling) until then or aborts on its
        budget."""
        join.ticks_waited += 1
        if not self.join_ready(join):
            return False
        self.scale_counters["joins_completed"] += 1
        return True

    async def abort_join(self, join: PendingJoin) -> None:
        """Failed-join rollback: stop the scheduler (drains anything in
        flight — with no shards there is nothing), release any leases it
        did claim, close the backend, and drop it from the roster. The
        fleet is exactly as it was before start_join."""
        self.scale_counters["joins_failed"] += 1
        replica = join.replica
        if replica in self.replicas:
            self.replicas.remove(replica)
        await replica.stop(release_leases=True)
        self._close_backend(replica)

    def pick_removal(self) -> FleetReplica:
        """Deterministic scale-down victim: the NEWEST replica (highest
        id). Bootstrap members persist, so repeated scale cycles churn
        the same tail instead of rotating ownership through the whole
        fleet."""
        return max(self.replicas, key=lambda r: r.replica_id)

    async def remove_replica(self, replica: FleetReplica) -> None:
        """Scale-down, drain-before-release (the PR 6 stop ordering, now
        on the controller path): the scheduler drains its in-flight
        decisions and completes their binds FIRST (leases still held, so
        the fenced binder passes), THEN leases release (survivors'
        fair-share claims converge on the freed shards), THEN the
        backend closes (socket teardown last — a decision in flight on
        the wire must never lose its transport before its bind lands).
        Refuses to shrink below one replica: a wave must never observe
        zero capacity."""
        if len(self.replicas) <= 1:
            raise ValueError("cannot remove the last replica")
        if replica not in self.replicas:
            raise ValueError(f"{replica.holder} is not in this fleet")
        self.replicas.remove(replica)
        try:
            # drains, then releases leases (FleetReplica.stop ordering)
            await replica.stop(release_leases=True)
        finally:
            self._close_backend(replica)
        self.scale_counters["removals"] += 1

    @staticmethod
    def _close_backend(replica: FleetReplica) -> None:
        close = getattr(replica.backend, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                logger.exception(
                    "%s: backend close failed during scale event",
                    replica.holder,
                )

    def aggregator(self, include_traces: bool = True):
        """A FleetAggregator over this fleet's replicas (observability/
        fleetview.py): per-replica stats sources (histograms merge
        bucket-wise into fleet percentiles) plus — because an in-process
        fleet shares one process-global flight recorder — the shared
        trace ring attached to replica 0's source only, so traces are
        pulled once, not N times."""
        from k8s_llm_scheduler_tpu.observability import spans
        from k8s_llm_scheduler_tpu.observability.fleetview import (
            FleetAggregator,
        )

        agg = FleetAggregator()
        for i, replica in enumerate(self.replicas):
            recorder = spans.flight if include_traces and i == 0 else None
            agg.add_source(
                replica.holder,
                lambda since, r=replica, rec=recorder:
                    r.telemetry(since_seq=since, recorder=rec),
            )
        return agg

    def get_stats(self) -> dict:
        totals = {
            "total_scheduled": 0,
            "failed_bindings": 0,
            "fenced_binds": 0,
        }
        per_replica = []
        for replica in self.replicas:
            stats = replica.get_stats()
            per_replica.append(stats)
            totals["total_scheduled"] += stats.get("total_scheduled", 0)
            totals["failed_bindings"] += stats.get("failed_bindings", 0)
            totals["fenced_binds"] += stats.get("fenced_binds", 0)
        out = {
            **totals,
            "n_shards": self.n_shards,
            "n_replicas": len(self.replicas),
            "scale": dict(self.scale_counters),
            "lease": self.store.gauges(),
            "l2": self.l2.stats(),
            "replicas": per_replica,
        }
        if self.kvplane is not None:
            # surfaces as llm_scheduler_kvplane_* in /metrics
            # (observability/metrics._flatten)
            out["kvplane"] = self.kvplane.gauges()
        return out
