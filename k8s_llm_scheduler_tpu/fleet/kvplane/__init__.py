"""Shared prefix-KV plane: one replica's snapshot prefill serves the
fleet. See store.py for the coherence protocol, pages.py for the unit
shipped, client.py for the per-replica pin path, stub.py for the
model-free protocol engine used by chaos and bench."""

from .client import KVPlaneClient
from .pages import (
    KVGeometry,
    KVGeometryError,
    PrefixPageSet,
    adopt_pages,
    export_pages,
    page_digest,
)
from .store import KVPlaneStore, KVPlaneStoreUnavailable
from .stub import StubPinEngine

__all__ = [
    "KVGeometry",
    "KVGeometryError",
    "KVPlaneClient",
    "KVPlaneStore",
    "KVPlaneStoreUnavailable",
    "PrefixPageSet",
    "StubPinEngine",
    "adopt_pages",
    "export_pages",
    "page_digest",
]
