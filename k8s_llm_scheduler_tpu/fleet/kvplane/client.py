"""KVPlaneClient: one replica's view of the shared prefix-KV plane.

Sits exactly where `engine.pin_prefix` used to be called from the
pinned-prefix manager (engine/admission/pinned.py): `pin(token_ids)`
keeps pin_prefix's return contract — (cache_key, prefix_epoch) — plus
the provenance tag (`local` | `shared`) that decision traces surface as
`kv_source`.

The pin path, in order:

1. **Sync** the store generation (a hot swap elsewhere in the fleet
   shows up here as a generation_sync; the engine's own prefix cache
   was already cleared by swap_params on this replica).
2. **Adopt**: lookup by content digest. A hit installs the peer's pages
   into the local engine (pages.adopt_pages) — no prefill paid.
3. **Elect**: on miss, run the single-filler election. Losing it means
   a peer is prefilling right now — re-check the store up to
   `wait_checks` times (cooperative, `yield_fn` between checks; the
   plane never sleeps a decision), then give up and prefill locally.
4. **Fill**: winning the election means prefill locally, export the
   pages, publish. A failed publish (fenced, stale generation, chaos
   stall) is not an error — the local pin already satisfied THIS
   replica's decision; only the fleet-wide dedup is lost.

Any KVPlaneStoreUnavailable anywhere degrades to a plain local
pin_prefix (counted as local_fallback) — the plane is an optimization
tier, never a correctness dependency. KVGeometryError, by contrast,
propagates: mixed geometry is a deployment bug.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from .pages import KVGeometry, adopt_pages, export_pages, page_digest
from .store import KVPlaneStore, KVPlaneStoreUnavailable


class KVPlaneClient:
    def __init__(
        self,
        store: KVPlaneStore,
        engine: Any,
        *,
        replica: str = "r0",
        transport: str = "host",
        wait_checks: int = 2,
        yield_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        self.store = store
        self.engine = engine
        self.replica = replica
        self.transport = transport
        self.wait_checks = int(wait_checks)
        self._yield = yield_fn
        self._known_generation = store.generation
        self.last_source = "local"
        self.counters = {
            "adoptions": 0,
            "publishes": 0,
            "publish_failures": 0,
            "local_fallbacks": 0,
            "store_misses": 0,
            "elections_won": 0,
            "elections_lost": 0,
            "generation_syncs": 0,
            "bytes_shipped": 0,
        }

    # -- generation sync ------------------------------------------------

    def sync_generation(self) -> int:
        g = self.store.generation
        if g != self._known_generation:
            self._known_generation = g
            self.counters["generation_syncs"] += 1
        return self._known_generation

    # -- pin path -------------------------------------------------------

    def _pin_local(self, token_ids: Sequence[int]) -> tuple[Any, int, str]:
        key, epoch = self.engine.pin_prefix(list(token_ids))
        self.last_source = "local"
        return key, epoch, "local"

    def pin(self, token_ids: Sequence[int]) -> tuple[Any, int, str]:
        """Pin a snapshot prefix, preferring fleet-shared pages.

        Returns (cache_key, prefix_epoch, source) where source is
        "shared" (pages adopted from a peer) or "local" (this replica
        prefilled — as the elected filler, or as a degradation)."""
        geometry = KVGeometry.of_engine(self.engine)
        digest = page_digest(token_ids)
        try:
            generation = self.sync_generation()
            pages = self.store.lookup(
                digest, geometry, generation=generation, holder=self.replica
            )
            if pages is not None:
                return self._adopt(pages)
            self.counters["store_misses"] += 1
            lease = self.store.try_fill(digest, self.replica)
            if lease is None:
                self.counters["elections_lost"] += 1
                # A peer holds the fill lease: poll a bounded number of
                # times for its publish before degrading. Bounded and
                # non-sleeping — a stalled filler costs us one local
                # prefill, not a stalled decision.
                for _ in range(self.wait_checks):
                    if self._yield is not None:
                        self._yield()
                    pages = self.store.lookup(
                        digest,
                        geometry,
                        generation=self.sync_generation(),
                        holder=self.replica,
                    )
                    if pages is not None:
                        return self._adopt(pages)
                self.counters["local_fallbacks"] += 1
                return self._pin_local(token_ids)
            self.counters["elections_won"] += 1
            return self._fill(token_ids, lease, generation)
        except KVPlaneStoreUnavailable:
            self.counters["local_fallbacks"] += 1
            return self._pin_local(token_ids)

    def _adopt(self, pages) -> tuple[Any, int, str]:
        key, epoch = adopt_pages(self.engine, pages)
        self.counters["adoptions"] += 1
        self.counters["bytes_shipped"] += pages.nbytes
        self.last_source = "shared"
        return key, epoch, "shared"

    def _fill(
        self, token_ids: Sequence[int], lease, generation: int
    ) -> tuple[Any, int, str]:
        key, epoch, source = self._pin_local(token_ids)
        pages = export_pages(
            self.engine,
            key,
            generation=generation,
            filler=self.replica,
            transport=self.transport,
        )
        if pages is not None:
            try:
                if self.store.publish(pages, lease):
                    self.counters["publishes"] += 1
                else:
                    self.counters["publish_failures"] += 1
            except KVPlaneStoreUnavailable:
                self.counters["publish_failures"] += 1
        return key, epoch, source

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        out = dict(self.counters)
        out["known_generation"] = self._known_generation
        out["last_source"] = self.last_source
        return out
