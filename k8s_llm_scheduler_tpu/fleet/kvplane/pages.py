"""Geometry-fingerprinted prefix-KV page sets: the unit the shared
prefix-KV plane (fleet/kvplane/) ships between replicas.

A page set is ONE snapshot prefix's dense KV stack — the exact
[L, cap, n_kv, hd] buffers the engine's prefix cache holds
(engine/engine._PrefixKV) — plus everything a peer needs to adopt it
without recomputing or resharding:

- the **content digest** of the pinned token ids. The delta encoder's
  pin keys (`pin-<seq>`, sched/delta.py) are replica-local sequence
  numbers; two replicas pinning the same cluster snapshot agree only on
  the TOKENS, so the plane keys pages by blake2b(token ids) and every
  replica that renders the same snapshot lands on the same entry.
- the **KV geometry** fingerprint: layer/head/dim/dtype shape AND the
  tensor-parallel group size the pages were placed for. A tp=4 replica
  adopts a tp=4 peer's pages directly (the head-sharded layout,
  engine/sharded/plane.py `prefix_kv`, is a property of the mesh both
  sides share); pages published under any OTHER geometry are refused
  loudly (KVGeometryError) — silently resharding would hide a fleet
  misconfiguration behind a perf cliff.
- the **transport arm**: `host` page sets carry numpy arrays (a
  device_get on publish, a device_put on adopt — the cross-process
  shape, and what a networked store would serialize), `d2d` page sets
  carry the filler's device arrays by reference (in-process fleets on
  one mesh: adoption is a device-to-device placement with no host
  round-trip).
- the **store generation** they were published under (store.py): the
  fleet-wide twin of `engine.prefix_epoch` — a hot swap bumps it once
  and every replica's next lookup refuses pre-swap pages.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Sequence


class KVGeometryError(RuntimeError):
    """Adoption refused: the page set's KV geometry does not match the
    adopting engine's (different model shape, dtype, or tp group size).
    Always a deployment error, never degraded around silently."""


@dataclasses.dataclass(frozen=True)
class KVGeometry:
    """The shape contract a prefix-KV page set must satisfy to be
    adoptable: model KV dimensions + the tp shard spec it was placed
    under. Frozen/hashable so stores can key entries by it."""

    n_layers: int
    n_kv_heads: int
    head_dim: int
    dtype: str
    tp: int = 1

    @classmethod
    def of_engine(cls, engine: Any) -> "KVGeometry":
        """Read an engine's serving geometry.

        Resolution order mirrors engine/sharded/geometry.member_tp: a
        `kv_geometry` attribute (stub/remote engines advertise without
        shipping a config), else the engine's (cfg, plane) pair — tp
        comes from the serving plane when one exists (tp>1 mesh), 1
        otherwise."""
        adv = getattr(engine, "kv_geometry", None)
        if isinstance(adv, KVGeometry):
            return adv
        if callable(adv):
            return adv()
        cfg = engine.cfg
        plane = getattr(engine, "plane", None)
        tp = int(plane.tp) if plane is not None else 1
        import numpy as np

        return cls(
            n_layers=int(cfg.n_layers),
            n_kv_heads=int(cfg.n_kv_heads),
            head_dim=int(cfg.head_dim),
            dtype=str(np.dtype(cfg.dtype)),
            tp=tp,
        )

    def describe(self) -> str:
        return (
            f"L{self.n_layers}xkv{self.n_kv_heads}xhd{self.head_dim}"
            f"/{self.dtype}/tp{self.tp}"
        )


def page_digest(token_ids: Sequence[int]) -> str:
    """Content address of a pinned snapshot prefix: blake2b over the
    token ids (not hash() — replicas must agree across processes, the
    same reason fleet/lease.shard_of uses it)."""
    h = hashlib.blake2b(digest_size=16)
    for t in token_ids:
        h.update(int(t).to_bytes(8, "big", signed=True))
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class PrefixPageSet:
    """One publishable snapshot-prefix KV stack (see module docstring)."""

    digest: str                 # page_digest(token_ids)
    token_ids: tuple[int, ...]
    geometry: KVGeometry
    k: Any                      # [L, cap, n_kv, hd]; np.ndarray | jax.Array
    v: Any
    transport: str              # "host" | "d2d"
    generation: int             # store generation at publish time
    filler: str                 # replica that paid the prefill

    def __post_init__(self) -> None:
        if self.transport not in ("host", "d2d"):
            raise ValueError(
                f"unknown kvplane transport {self.transport!r} "
                "(known: host, d2d)"
            )

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    @property
    def length(self) -> int:
        return len(self.token_ids)


def export_pages(
    engine: Any,
    cache_key: tuple[int, ...],
    *,
    generation: int,
    filler: str,
    transport: str = "host",
) -> PrefixPageSet | None:
    """Build a publishable page set from an engine's cached (pinned)
    prefix. Returns None when the entry is gone (evicted between pin and
    export — the filler then simply doesn't publish).

    The export ships the WHOLE capacity buffer, padding included, not a
    `[:length]` slice: the adopter installs a buffer bit-identical to
    the filler's local one, so adopted-vs-local token identity holds by
    construction and no new pad-shape ever reaches the jitted programs.
    """
    kv = engine.export_prefix_kv(cache_key)
    if kv is None:
        return None
    k, v = kv
    if transport == "host":
        import jax

        k, v = jax.device_get(k), jax.device_get(v)
    return PrefixPageSet(
        digest=page_digest(cache_key),
        token_ids=tuple(cache_key),
        geometry=KVGeometry.of_engine(engine),
        k=k,
        v=v,
        transport=transport,
        generation=int(generation),
        filler=filler,
    )


def adopt_pages(engine: Any, pages: PrefixPageSet) -> tuple[tuple[int, ...], int]:
    """Install a peer's page set into `engine` as a pinned prefix.

    Refuses loudly on geometry mismatch BEFORE touching the engine —
    the tp=4/tp=2 case the sharded plane makes fatal: the kv-head axis
    of the shipped buffer was laid out for a different shard spec.
    Returns (cache_key, prefix_epoch), exactly pin_prefix's contract."""
    want = KVGeometry.of_engine(engine)
    if pages.geometry != want:
        raise KVGeometryError(
            f"cannot adopt prefix-KV pages published by {pages.filler!r} "
            f"with geometry {pages.geometry.describe()}: this replica "
            f"serves {want.describe()} (pages must be re-prefilled, not "
            "resharded)"
        )
    return engine.adopt_prefix_pages(list(pages.token_ids), pages.k, pages.v)
