"""KVPlaneStore: the fleet-shared prefix-KV tier.

One replica's snapshot prefill serves the whole fleet: the first
replica to miss on a snapshot digest wins a **fill lease** (the same
epoch-fenced lease machinery that owns scheduling shards,
fleet/lease.py — a digest hashes to a fill shard, `try_acquire` elects
exactly one filler, `check_fence` rejects a filler that lost its lease
before publishing). Everyone else either adopts the published pages or
degrades to a local prefill; the store never blocks a decision.

Generation protocol (the TieredDecisionCache design, fleet/cache.py,
applied to KV): the store's `generation` is the fleet-wide twin of the
per-engine `prefix_epoch`. Hot swaps bump it ONCE
(rollout/hotswap.HotSwapper / rollout/canary.staggered_swap) and the
bump clears every entry — pages prefilled under old weights are wrong
under new weights, full stop. Lookups present the generation the client
last synced; a stale presentation is refused (counted, never served),
and a filler that publishes after a bump publishes into the void
(stale_publishes) rather than poisoning the new generation.

Geometry: entries are keyed by digest and stamped with the publisher's
KVGeometry (tp shard spec included). A lookup whose geometry differs
from the stored entry's raises KVGeometryError — loud refusal, because
a mixed-geometry fleet is a misconfiguration, not a cache miss.

Chaos seam: `fault_seam` (chaos/faults.Seam for the "kvplane" seam)
is consulted once per store operation — `store_down` makes the op raise
KVPlaneStoreUnavailable (clients degrade to local prefill),
`fill_stall` kills a publish mid-flight (the fill lease is NOT released:
waiters see neither pages nor a free lease until the TTL reaps it,
exactly what a dead filler looks like), `stale_generation` ages the
presented generation so adoption is refused.

All judgments use the injected clock; nothing here sleeps.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from .pages import KVGeometry, KVGeometryError, PrefixPageSet
from ..lease import Lease, LeaseStore


class KVPlaneStoreUnavailable(RuntimeError):
    """The shared KV tier cannot be reached; callers degrade to local
    prefill (never an error surfaced to a decision)."""


class KVPlaneStore:
    """In-memory reference store for the shared prefix-KV plane.

    Single-process fleets share the object directly; the method surface
    (lookup / try_fill / publish / bump_generation, all keyed by content
    digest + generation) is what a networked backend would expose."""

    def __init__(
        self,
        *,
        fill_ttl_s: float = 5.0,
        max_entries: int = 8,
        n_fill_shards: int = 64,
        clock: Callable[[], float] = time.monotonic,
        lease_store: Optional[LeaseStore] = None,
    ) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self.max_entries = int(max_entries)
        self.lease = lease_store or LeaseStore(
            n_fill_shards, ttl_s=fill_ttl_s, clock=clock
        )
        # digest -> PrefixPageSet, LRU order; current generation only
        # (a bump clears the dict, so no entry ever carries a stale
        # generation — the stamp exists for clients that cached a
        # reference across the bump).
        self._entries: "OrderedDict[str, PrefixPageSet]" = OrderedDict()
        self.generation = 0
        self.fault_seam = None  # chaos/faults.Seam("kvplane") when under chaos
        self.counters = {
            "fills": 0,
            "adoptions": 0,
            "bytes_shipped": 0,
            "evictions": 0,
            "stale_rejections": 0,
            "stale_publishes": 0,
            "geometry_refusals": 0,
            "store_outages": 0,
            "fill_stalls": 0,
            "generation_bumps": 0,
        }

    # -- fault plumbing -------------------------------------------------

    def _check_up(self, holder: str) -> None:
        seam = self.fault_seam
        if seam is not None and seam.should("store_down", key=holder):
            with self._lock:
                self.counters["store_outages"] += 1
            raise KVPlaneStoreUnavailable(
                f"kvplane store unreachable from {holder!r}"
            )

    def _presented_generation(self, generation: int, holder: str) -> int:
        seam = self.fault_seam
        if seam is not None and seam.should("stale_generation", key=holder):
            return int(generation) - 1
        return int(generation)

    # -- fill election --------------------------------------------------

    def fill_shard(self, digest: str) -> int:
        """Map a snapshot digest onto a fill-lease shard (blake2b, the
        fleet/lease.shard_of discipline — stable across processes)."""
        h = hashlib.blake2b(digest.encode("utf-8"), digest_size=8)
        return int.from_bytes(h.digest(), "big") % self.lease.n_shards

    def try_fill(self, digest: str, holder: str) -> Optional[Lease]:
        """Run the single-filler election for `digest`. Returns the fill
        lease when `holder` wins (it now owes a publish or a TTL
        expiry), None when another replica already holds the fill."""
        self._check_up(holder)
        return self.lease.try_acquire(self.fill_shard(digest), holder)

    # -- data path ------------------------------------------------------

    def lookup(
        self,
        digest: str,
        geometry: KVGeometry,
        *,
        generation: int,
        holder: str,
    ) -> Optional[PrefixPageSet]:
        """Fetch published pages for `digest`, or None on miss.

        Refusals: a generation older than the store's (stale client —
        it must sync and re-pin, not adopt pre-swap KV) returns None and
        counts `stale_rejections`; a geometry mismatch against the
        stored entry raises KVGeometryError (see module docstring)."""
        self._check_up(holder)
        presented = self._presented_generation(generation, holder)
        with self._lock:
            if presented != self.generation:
                self.counters["stale_rejections"] += 1
                return None
            pages = self._entries.get(digest)
            if pages is None:
                return None
            if pages.geometry != geometry:
                self.counters["geometry_refusals"] += 1
                raise KVGeometryError(
                    f"kvplane entry {digest[:12]} was published for "
                    f"{pages.geometry.describe()} but {holder!r} serves "
                    f"{geometry.describe()}"
                )
            self._entries.move_to_end(digest)
            self.counters["adoptions"] += 1
            self.counters["bytes_shipped"] += pages.nbytes
            return pages

    def publish(self, pages: PrefixPageSet, lease: Lease) -> bool:
        """Publish freshly-prefilled pages under a fill lease.

        Returns False (entry NOT stored) when the filler's lease was
        fenced off, the store's generation moved past the pages', or a
        `fill_stall` fault kills the publish mid-flight. In the stall
        case the lease is deliberately left held — a filler that died
        mid-publish cannot release, so waiters degrade locally until
        the TTL reaps the lease. That asymmetry is what the
        kv-plane-outage regime exercises."""
        self._check_up(pages.filler)
        seam = self.fault_seam
        if seam is not None and seam.should("fill_stall", key=pages.filler):
            with self._lock:
                self.counters["fill_stalls"] += 1
            return False
        if not self.lease.check_fence(lease.shard_id, pages.filler, lease.epoch):
            return False
        with self._lock:
            if pages.generation != self.generation:
                self.counters["stale_publishes"] += 1
                return False
            self._entries[pages.digest] = pages
            self._entries.move_to_end(pages.digest)
            self.counters["fills"] += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.counters["evictions"] += 1
        self.lease.release(lease.shard_id, pages.filler)
        return True

    # -- generation protocol -------------------------------------------

    def bump_generation(self) -> int:
        """Fleet-wide invalidation: weights changed (hot swap) or the
        pinned snapshot universe was rebuilt. Clears every entry —
        mirrors engine.swap_params clearing the local prefix cache."""
        with self._lock:
            self.generation += 1
            self._entries.clear()
            self.counters["generation_bumps"] += 1
            return self.generation

    # -- introspection --------------------------------------------------

    def gauges(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["generation"] = self.generation
            out["entries"] = len(self._entries)
            out["resident_bytes"] = sum(
                p.nbytes for p in self._entries.values()
            )
            return out

    # alias so fleet telemetry paths that expect .stats() work too
    stats = gauges
