"""StubPinEngine: a model-free engine that speaks the pin/adopt surface.

The chaos harness and bench fleet arms need MANY replicas exercising
the kvplane protocol (elections, adoption, generation bumps, outages)
where loading even the micro model per replica would drown the thing
being measured. This stub implements exactly the engine methods the
plane touches — pin_prefix / adopt_prefix_pages / export_prefix_kv /
unpin_prefix / pin_alive / prefix_epoch / kv_geometry — with KV that is
a *pure deterministic function of the token ids* (a tiny seeded-hash
fill). That purity is the correctness probe: a replica that adopted
pages holds byte-identical KV to one that "prefilled" locally, so the
chaos harness can assert zero correctness loss by comparing digests,
no model required.

Counters mirror the real engine's prefix stats (prefix_prefills,
prefill_tokens, adopted_prefixes, prefix_hits) so fleet telemetry and
bench arithmetic read the same names either way.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

from .pages import KVGeometry

_STUB_GEOMETRY = KVGeometry(
    n_layers=2, n_kv_heads=2, head_dim=4, dtype="float32", tp=1
)


def _stub_kv(token_ids: Sequence[int], geom: KVGeometry) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic [L, S, n_kv, hd] KV derived from the token ids —
    the same ids yield the same bytes on every replica."""
    h = hashlib.blake2b(digest_size=8)
    for t in token_ids:
        h.update(int(t).to_bytes(8, "big", signed=True))
    seed = int.from_bytes(h.digest(), "big") % (2**32)
    rng = np.random.default_rng(seed)
    shape = (geom.n_layers, max(1, len(token_ids)), geom.n_kv_heads, geom.head_dim)
    k = rng.standard_normal(shape).astype(geom.dtype)
    v = rng.standard_normal(shape).astype(geom.dtype)
    return k, v


class StubPinEngine:
    def __init__(self, *, geometry: KVGeometry | None = None, prefill_cost_per_token: int = 1) -> None:
        self.kv_geometry = geometry or _STUB_GEOMETRY
        self.prefix_epoch = 0
        self._cache: dict[tuple[int, ...], tuple[np.ndarray, np.ndarray]] = {}
        self._pinned: set[tuple[int, ...]] = set()
        self._cost = int(prefill_cost_per_token)
        self.stats = {
            "prefix_prefills": 0,
            "prefill_tokens": 0,
            "adopted_prefixes": 0,
            "prefix_hits": 0,
            "pinned_prefixes": 0,
            "pin_evictions": 0,
        }

    # -- pin surface ----------------------------------------------------

    def pin_prefix(self, token_ids: Sequence[int]) -> tuple[tuple[int, ...], int]:
        key = tuple(int(t) for t in token_ids)
        if key in self._cache:
            self.stats["prefix_hits"] += 1
        else:
            self._cache[key] = _stub_kv(key, self.kv_geometry)
            self.stats["prefix_prefills"] += 1
            self.stats["prefill_tokens"] += len(key) * self._cost
        self._pinned.add(key)
        self.stats["pinned_prefixes"] += 1
        return key, self.prefix_epoch

    def adopt_prefix_pages(
        self, token_ids: Sequence[int], k: np.ndarray, v: np.ndarray
    ) -> tuple[tuple[int, ...], int]:
        key = tuple(int(t) for t in token_ids)
        self._cache[key] = (np.asarray(k), np.asarray(v))
        self._pinned.add(key)
        self.stats["adopted_prefixes"] += 1
        return key, self.prefix_epoch

    def export_prefix_kv(self, cache_key: Sequence[int]):
        key = tuple(int(t) for t in cache_key)
        return self._cache.get(key)

    def unpin_prefix(self, cache_key: Sequence[int]) -> bool:
        key = tuple(int(t) for t in cache_key)
        if key in self._pinned:
            self._pinned.discard(key)
            self.stats["pin_evictions"] += 1
            return True
        return False

    def pin_alive(self, cache_key, epoch: int) -> bool:
        key = tuple(int(t) for t in cache_key)
        return (
            epoch == self.prefix_epoch
            and key in self._pinned
            and key in self._cache
        )

    # -- swap simulation ------------------------------------------------

    def bump_epoch(self) -> int:
        """What swap_params does to the prefix plane: clear + epoch++."""
        self._cache.clear()
        self._pinned.clear()
        self.prefix_epoch += 1
        return self.prefix_epoch

    # -- correctness probe ----------------------------------------------

    def kv_digest(self, cache_key: Sequence[int]) -> str | None:
        """Digest of the resident KV for `cache_key` — adopted pages and
        a local prefill of the same ids must agree byte-for-byte."""
        kv = self.export_prefix_kv(cache_key)
        if kv is None:
            return None
        h = hashlib.blake2b(digest_size=16)
        h.update(np.ascontiguousarray(kv[0]).tobytes())
        h.update(np.ascontiguousarray(kv[1]).tobytes())
        return h.hexdigest()

    def get_stats(self) -> dict:
        return dict(self.stats)
