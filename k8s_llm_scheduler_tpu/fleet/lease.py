"""Leased watch-space sharding — the fleet's ownership protocol.

A fleet of N scheduler replicas must partition the pending-pod watch
space so each pod is decided and bound by EXACTLY ONE replica, and so a
dead replica's share is picked up without either orphaning pods forever
or binding them twice. The protocol here is the standard lease one
(Kubernetes coordination.k8s.io Leases, etcd leases):

- the watch space is split into `n_shards` hash shards keyed on the
  pod's namespace/name (`shard_of`) — a pod's shard never changes;
- each shard is owned via a renewable lease with TTL expiry. A lease
  carries an `epoch` (fencing token) that increments on every
  acquisition, so a holder that lost its lease (expired while it was
  paused/partitioned) can detect staleness instead of acting on it;
- a dead replica simply stops renewing; after `ttl_s` its shards read
  as free and any live replica may claim them. The claimer re-lists the
  shard's still-pending pods and schedules them (fleet/frontend.py) —
  pods the dead replica already bound are no longer pending, so the
  rebind pass is idempotent, and the claimer's fenced binder refuses to
  bind pods of shards it no longer owns.

`LeaseStore` here is the in-process twin of that protocol (shared by
the fleet's replicas in tests, benches, and single-process
deployments). A multi-process deployment backs the same API with
apiserver Lease objects — one Lease per shard, `holder` =
holderIdentity, `epoch` = leaseTransitions — without touching anything
above this seam. The store is thread-safe and takes an injectable
clock so failover tests advance time instead of sleeping.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import math
import os
import threading
import time
from pathlib import Path
from typing import Callable, Iterable

logger = logging.getLogger(__name__)


def shard_of(namespace: str, name: str, n_shards: int) -> int:
    """Stable shard id for a pod identity. blake2b, not hash(): Python's
    string hash is salted per process, and two replicas MUST agree on
    every pod's shard or pods fall between filters."""
    if n_shards <= 1:
        return 0
    digest = hashlib.blake2b(
        f"{namespace}/{name}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") % n_shards


@dataclasses.dataclass
class Lease:
    shard_id: int
    holder: str
    epoch: int          # fencing token: bumps on every (re)acquisition
    expires_at: float   # store-clock deadline; renewals push it forward


class LeaseExpired(RuntimeError):
    """A renew/release was attempted on a lease the caller no longer
    holds (expired and possibly re-acquired by someone else)."""


class LeaseStoreUnavailable(RuntimeError):
    """The lease store could not be reached (chaos-injected partition; a
    k8s-backed store maps this to apiserver connectivity errors). The
    caller must treat it as a MISSED operation — which is exactly the
    failure mode TTL leases exist to survive."""


class LeaseStore:
    """Shard -> lease table with TTL expiry. All judgments use the
    injected clock; nothing here sleeps."""

    def __init__(
        self,
        n_shards: int,
        ttl_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        # Chaos seam (chaos/faults.py, seam "lease"): None in production.
        # Interpreted per MUTATING caller identity: partition (the store
        # is unreachable for that holder), lost_renewal (the renewal is
        # silently not applied — the holder believes it landed), and
        # clock_skew (the holder's mutations are judged at now+skew_s).
        self.fault_seam = None
        self._leases: dict[int, Lease] = {}
        self._epochs: dict[int, int] = {}  # survives expiry: epochs only grow
        # replica presence, independent of shard ownership: a NEWCOMER
        # holds no leases yet, but must count toward everyone's fair-
        # share target or the incumbents never shed and it starves. A
        # k8s-backed store maps this to the replica's own identity Lease.
        self._heartbeats: dict[str, float] = {}
        self._lock = threading.Lock()
        # lease-plane observability (llm_scheduler_lease_* gauges): the
        # autoscale controller and scrapers read these through the fleet
        # stats tree — monotone counters, mutated only under the lock
        self.counters: dict[str, int] = {
            "acquisitions": 0,      # fresh epochs granted (not renewals)
            "releases": 0,          # voluntary releases that landed
            "fence_checks": 0,
            "fence_rejections": 0,  # check_fence answered False
        }

    # -------------------------------------------------------- backend hook
    def _state_changed_locked(self) -> None:
        """Called at the end of every mutating operation with self._lock
        held — the ONE seam a durable backend overrides to persist. The
        in-memory default is a no-op (zero cost on the test-default
        store); FileLeaseStore writes its state file here, so both
        backends share every line of protocol logic and can only
        diverge in storage, never in semantics."""

    # ----------------------------------------------------------- chaos seam
    def _chaos_check(self, holder: str) -> None:
        """Partition gate for mutating ops: a partitioned holder's call
        never reaches the store (reads stay live — they model OTHER
        observers, and the invariant monitor uses them as the
        authority)."""
        seam = self.fault_seam
        if seam is not None and seam.should("partition", key=holder) is not None:
            raise LeaseStoreUnavailable(
                f"lease store unreachable for {holder} (chaos partition)"
            )

    def _now_for(self, holder: str) -> float:
        """The clock a holder's mutations are judged by: the store clock,
        plus any chaos-injected skew for that holder — the 'two replicas
        disagree about time' regime lease fencing must survive."""
        now = self._clock()
        seam = self.fault_seam
        if seam is not None:
            event = seam.should("clock_skew", key=holder)
            if event is not None:
                now += float(event.param("skew_s", 0.0))
        return now

    # -------------------------------------------------------------- queries
    def holder_of(self, shard_id: int) -> str | None:
        """Current unexpired holder, or None (free or expired)."""
        now = self._clock()
        with self._lock:
            lease = self._leases.get(shard_id)
            if lease is None or lease.expires_at <= now:
                return None
            return lease.holder

    def heartbeat(self, holder: str) -> None:
        """Record replica presence (TTL-expired like a lease). Managers
        heartbeat every tick, so a dead replica drops out of everyone's
        fair-share denominator after ttl_s."""
        self._chaos_check(holder)
        now = self._now_for(holder)
        with self._lock:
            self._heartbeats[holder] = now + self.ttl_s
            # opportunistic purge so the table can't grow unbounded
            # across replica generations
            dead = [h for h, t in self._heartbeats.items() if t <= now]
            for h in dead:
                del self._heartbeats[h]
            self._state_changed_locked()

    def retract_heartbeat(self, holder: str) -> None:
        """Remove a holder's presence record immediately (clean
        shutdown/scale-down). Without this the departed replica reads
        as a zero-shard STARVED peer for a full TTL, and the
        yield-to-most-starved claim rule would hold every freed shard
        unclaimed for it — pods on those shards would strand exactly as
        long. A crash does NOT retract: its heartbeat ages out with its
        leases, which is the failover path."""
        self._chaos_check(holder)
        with self._lock:
            self._heartbeats.pop(holder, None)
            self._state_changed_locked()

    def live_holders(self) -> set[str]:
        """Replicas that are PRESENT: unexpired lease holders plus
        unexpired heartbeats (a newcomer with no shards yet)."""
        now = self._clock()
        with self._lock:
            holders = {
                l.holder for l in self._leases.values() if l.expires_at > now
            }
            holders.update(
                h for h, t in self._heartbeats.items() if t > now
            )
            return holders

    def holdings(self) -> dict[str, int]:
        """Unexpired lease count per PRESENT holder (heartbeat-only
        newcomers appear at 0) — the census the fair-share shed rule
        needs to see a starved peer."""
        now = self._clock()
        with self._lock:
            out = {h: 0 for h, t in self._heartbeats.items() if t > now}
            for lease in self._leases.values():
                if lease.expires_at > now:
                    out[lease.holder] = out.get(lease.holder, 0) + 1
            return out

    def check_fence(self, shard_id: int, holder: str, epoch: int) -> bool:
        """Bind-time fencing-token check: does the store, NOW, hold an
        unexpired lease for `shard_id` by `holder` at exactly `epoch`?
        The fenced binder (fleet/frontend._FencedBinder) asks this before
        every bind, so a replica whose lease expired or was re-acquired
        (a stale fencing token) cannot land a bind — and a replica that
        cannot REACH the store to ask fails CLOSED (the
        LeaseStoreUnavailable from the partition gate propagates; the
        caller refuses the bind). Judged on the store's own clock: skew
        on the asking holder's side must not extend its authority."""
        self._chaos_check(holder)
        now = self._clock()
        with self._lock:
            lease = self._leases.get(shard_id)
            ok = (
                lease is not None
                and lease.expires_at > now
                and lease.holder == holder
                and lease.epoch == epoch
            )
            self.counters["fence_checks"] += 1
            if not ok:
                self.counters["fence_rejections"] += 1
            return ok

    def snapshot(self) -> dict[int, Lease]:
        """Copy of all UNEXPIRED leases (for /metrics and cli fleet)."""
        now = self._clock()
        with self._lock:
            return {
                sid: dataclasses.replace(lease)
                for sid, lease in self._leases.items()
                if lease.expires_at > now
            }

    # ------------------------------------------------------------ mutations
    def try_acquire(self, shard_id: int, holder: str) -> Lease | None:
        """Claim a free/expired shard (epoch bumps — a new ownership term)
        or renew one already held by `holder` (epoch unchanged). Returns
        None when another holder's lease is still live."""
        if not 0 <= shard_id < self.n_shards:
            raise ValueError(f"shard {shard_id} out of range 0..{self.n_shards - 1}")
        self._chaos_check(holder)
        now = self._now_for(holder)
        with self._lock:
            lease = self._leases.get(shard_id)
            if lease is not None and lease.expires_at > now:
                if lease.holder != holder:
                    return None
                lease.expires_at = now + self.ttl_s
                self._state_changed_locked()
                return dataclasses.replace(lease)
            epoch = self._epochs.get(shard_id, 0) + 1
            self._epochs[shard_id] = epoch
            lease = Lease(shard_id, holder, epoch, now + self.ttl_s)
            self._leases[shard_id] = lease
            self.counters["acquisitions"] += 1
            self._state_changed_locked()
            logger.debug(
                "lease: shard %d -> %s (epoch %d)", shard_id, holder, epoch
            )
            return dataclasses.replace(lease)

    def renew(self, shard_id: int, holder: str, epoch: int) -> Lease:
        """Extend a held lease. Raises LeaseExpired when the lease is
        gone, expired, or held under a different epoch — the caller must
        stop acting for this shard (its fencing token is stale)."""
        self._chaos_check(holder)
        now = self._now_for(holder)
        seam = self.fault_seam
        with self._lock:
            lease = self._leases.get(shard_id)
            if (
                lease is None
                or lease.expires_at <= now
                or lease.holder != holder
                or lease.epoch != epoch
            ):
                raise LeaseExpired(
                    f"shard {shard_id}: lease not held by {holder}@{epoch}"
                )
            if seam is not None and seam.should(
                "lost_renewal", key=holder
            ) is not None:
                # chaos: the renewal is silently NOT applied — the holder
                # walks away believing it renewed while the lease keeps
                # aging toward TTL expiry (a dropped apiserver write)
                return dataclasses.replace(lease)
            lease.expires_at = now + self.ttl_s
            self._state_changed_locked()
            return dataclasses.replace(lease)

    def release(self, shard_id: int, holder: str) -> bool:
        """Voluntary release (clean shutdown): the shard reads free
        immediately instead of after TTL."""
        self._chaos_check(holder)
        with self._lock:
            lease = self._leases.get(shard_id)
            if lease is None or lease.holder != holder:
                return False
            del self._leases[shard_id]
            self.counters["releases"] += 1
            self._state_changed_locked()
            return True

    def gauges(self) -> dict:
        """Flat lease-store view for the fleet stats tree (rendered as
        llm_scheduler_lease_* gauges by observability/metrics._flatten).
        Holder names are sanitized to metric-name-legal identifiers."""
        holdings = self.holdings()
        with self._lock:
            counters = dict(self.counters)
        leased = sum(holdings.values())
        return {
            **counters,
            "shards": self.n_shards,
            "leased_shards": leased,
            "free_shards": self.n_shards - leased,
            "live_holders": len(holdings),
            "holdings": {
                h.replace("-", "_").replace(".", "_"): n
                for h, n in sorted(holdings.items())
            },
        }


class FileLeaseStore(LeaseStore):
    """Durable LeaseStore backend: identical TTL/epoch-fencing semantics
    (every protocol line is inherited — only storage differs), persisted
    to one JSON state file with the registry's write-aside + os.replace
    + fsync discipline (rollout/registry.py) on every mutation.

    Restart semantics: epochs, leases, and heartbeats survive a process
    death, so a restarted replica re-acquiring its own unexpired lease
    RENEWS it (same epoch — its journaled bind intents stay fenced
    valid), while a lease that expired during the outage re-acquires
    under a BUMPED epoch exactly as a failover claim would. The clock
    caveat is the caller's: lease expiry is judged on the injected
    clock, so a durable deployment must inject a clock whose values
    mean the same thing across restarts (the chaos harness injects its
    virtual store clock; a production deployment maps this store to
    Kubernetes coordination.k8s.io Lease objects, where the apiserver
    owns the clock, and never reaches this file backend).

    Mutation cost: one ~1KB atomic file write under the store lock —
    the in-memory store stays the default everywhere latency matters;
    this backend exists so crash-restart tests and single-node durable
    deployments exercise the SAME semantics they would get from a
    k8s-backed store."""

    def __init__(
        self,
        path: str | Path,
        n_shards: int,
        ttl_s: float = 5.0,
        # wall clock, NOT monotonic: persisted expires_at values must
        # mean the same thing after a process restart or host reboot —
        # a monotonic deadline from a long-uptime boot would read as
        # unexpired for days on a freshly-booted host, freezing failover
        # for the dead incarnation's shards. Tests inject virtual clocks
        # as with the base store.
        clock: Callable[[], float] = time.time,
    ) -> None:
        super().__init__(n_shards, ttl_s=ttl_s, clock=clock)
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists():
            self._load()

    def _load(self) -> None:
        with open(self.path, encoding="utf-8") as fh:
            data = json.load(fh)
        if int(data.get("n_shards", self.n_shards)) != self.n_shards:
            raise ValueError(
                f"lease store {self.path} was written for "
                f"{data.get('n_shards')} shards, not {self.n_shards}"
            )
        with self._lock:
            self._epochs = {
                int(sid): int(epoch)
                for sid, epoch in (data.get("epochs") or {}).items()
            }
            self._leases = {
                int(sid): Lease(
                    shard_id=int(sid),
                    holder=rec["holder"],
                    epoch=int(rec["epoch"]),
                    expires_at=float(rec["expires_at"]),
                )
                for sid, rec in (data.get("leases") or {}).items()
            }
            self._heartbeats = {
                h: float(t)
                for h, t in (data.get("heartbeats") or {}).items()
            }

    def _state_changed_locked(self) -> None:
        """Persist the whole (small) table atomically: write-aside,
        fsync, one os.replace — a crash mid-write leaves the previous
        state file intact, never a torn one."""
        data = {
            "n_shards": self.n_shards,
            "epochs": {str(s): e for s, e in self._epochs.items()},
            "leases": {
                str(s): {
                    "holder": l.holder,
                    "epoch": l.epoch,
                    "expires_at": l.expires_at,
                }
                for s, l in self._leases.items()
            },
            "heartbeats": dict(self._heartbeats),
        }
        tmp = self.path.with_name(self.path.name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)


class LeaseManager:
    """One replica's lease agent: renew what it holds, claim its fair
    share of free/expired shards, surface gains and losses.

    `tick()` is the whole protocol — deterministic, re-entrant-safe, and
    callable directly by tests (no background thread needed). `start()`
    runs it on a daemon thread every `renew_interval_s` for live
    deployments; the interval must be comfortably under the store TTL
    (the classic lease rule: renew at most every ttl/3).

    Fair share: a replica targets ceil(n_shards / live_holders) shards —
    a static target would either orphan shards (too low) or let one
    replica monopolize the space (too high). Newly observed holders push
    the target down, and a replica holding MORE than its target sheds at
    most ONE shard per tick (releases it; an under-target peer claims it
    and its rebind pass picks up any pods that arrived in the gap).
    One-per-tick keeps rebalancing gentle — a scale-up drains ownership
    over a few renew intervals instead of thrashing — and the system is
    stable at the balanced point (nobody over target, nobody sheds).
    Decisions in flight for a shed shard are fenced at bind time exactly
    like post-failover stragglers, so rebalancing cannot double-bind.

    `on_gain(shard_ids)` fires AFTER the tick holds the new leases — the
    frontend uses it to re-list and rebind the gained shards' pending
    pods. `on_loss(shard_ids)` fires when renewal discovers expiry (the
    replica was paused past TTL) so the frontend can fence itself.
    """

    def __init__(
        self,
        store: LeaseStore,
        holder: str,
        renew_interval_s: float = 1.5,
        on_gain: Callable[[frozenset[int]], None] | None = None,
        on_loss: Callable[[frozenset[int]], None] | None = None,
    ) -> None:
        self.store = store
        self.holder = holder
        self.renew_interval_s = float(renew_interval_s)
        self.on_gain = on_gain
        self.on_loss = on_loss
        self._held: dict[int, Lease] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # per-holder lease agent counters (llm_scheduler_lease_* via the
        # replica stats tree): the shed/claim churn rate is the autoscale
        # controller's view of how settled membership is
        self.counters: dict[str, int] = {
            "ticks": 0,
            "claims": 0,
            "sheds": 0,
            "losses": 0,
            "renewals": 0,
            "store_unavailable": 0,
        }

    # -------------------------------------------------------------- queries
    def owned(self) -> frozenset[int]:
        """Shards this replica currently believes it holds. The fencing
        check at bind time (fleet/frontend._FencedBinder) re-validates
        against the STORE — this local view can lag one tick behind."""
        with self._lock:
            return frozenset(self._held)

    def owns(self, shard_id: int) -> bool:
        with self._lock:
            return shard_id in self._held

    def epoch_of(self, shard_id: int) -> int | None:
        with self._lock:
            lease = self._held.get(shard_id)
            return None if lease is None else lease.epoch

    def stats(self) -> dict:
        """This agent's lease-plane counters + current holdings (the
        `lease` subtree of FleetReplica.get_stats, flattened into
        llm_scheduler_lease_* gauges)."""
        with self._lock:
            return {**self.counters, "held": len(self._held)}

    def adopt(self, lease: Lease) -> None:
        """Take ownership of a lease acquired on this holder's behalf
        (fleet bootstrap: assign_initial claims in the store, then each
        manager adopts its share so renewal takes over)."""
        if lease.holder != self.holder:
            raise ValueError(
                f"cannot adopt lease held by {lease.holder!r} "
                f"into manager {self.holder!r}"
            )
        with self._lock:
            self._held[lease.shard_id] = lease

    # ------------------------------------------------------------- protocol
    def tick(self) -> tuple[frozenset[int], frozenset[int]]:
        """One renew + claim pass. Returns (gained, lost) shard sets and
        fires the callbacks (gains after the claim, losses after the
        renew sweep). An unreachable store (LeaseStoreUnavailable —
        chaos partition, apiserver outage) aborts the REST of the tick
        (missed ticks ARE the failure mode TTL leases absorb, and one
        partitioned replica must not abort a shared tick driver) — but
        the tick is not atomic: ownership changes already applied before
        the failure are real, so their callbacks still fire (a gained
        shard whose on_gain rebind never ran would strand its pending
        pods forever: no later tick re-reports a shard already held)."""
        gained: set[int] = set()
        lost: set[int] = set()
        with self._lock:
            self.counters["ticks"] += 1
        try:
            self._tick_inner(gained, lost)
        except LeaseStoreUnavailable as exc:
            with self._lock:
                self.counters["store_unavailable"] += 1
            logger.warning(
                "lease tick aborted for %s (%s): %d gain(s)/%d loss(es) "
                "already applied, callbacks firing for those",
                self.holder, exc, len(gained), len(lost),
            )
        lost_f, gained_f = frozenset(lost), frozenset(gained)
        if lost_f and self.on_loss is not None:
            self.on_loss(lost_f)
        if gained_f and self.on_gain is not None:
            self.on_gain(gained_f)
        return gained_f, lost_f

    def _tick_inner(self, gained: set, lost: set) -> None:
        """The store-touching pass: mutates `gained`/`lost` IN PLACE as
        each ownership change lands, so an abort mid-tick leaves the
        caller an exact record of what actually changed."""
        self.store.heartbeat(self.holder)
        with self._lock:
            held = dict(self._held)
        for sid, lease in held.items():
            try:
                renewed = self.store.renew(sid, self.holder, lease.epoch)
            except LeaseExpired:
                lost.add(sid)
            else:
                with self._lock:
                    self.counters["renewals"] += 1
                    if sid in self._held:
                        self._held[sid] = renewed
        if lost:
            with self._lock:
                self.counters["losses"] += len(lost)
                for sid in lost:
                    self._held.pop(sid, None)
            logger.warning(
                "lease manager %s: lost shards %s (renewal expired)",
                self.holder, sorted(lost),
            )

        holdings = self.store.holdings()
        holdings.setdefault(self.holder, 0)  # we just heartbeated
        n_live = len(holdings)
        target = math.ceil(self.store.n_shards / n_live)
        floor_share = self.store.n_shards // n_live
        # Ceil alone starves a newcomer whenever the incumbents' holdings
        # already EQUAL ceil (16 shards at 4->5 replicas: ceil=4, everyone
        # holds 4, nobody over). A peer below the floor is the signal
        # that the remainder is maldistributed: shed down to the floor
        # until no live holder is starved (balanced states have every
        # holder at floor or floor+1 with nobody below floor — stable).
        starved = any(
            h != self.holder and count < floor_share
            for h, count in holdings.items()
        )
        with self._lock:
            n_held = len(self._held)
            over = n_held > target or (starved and n_held > floor_share)
            shed = max(self._held) if over and self._held else None
        if shed is not None:
            # one shard per tick: gentle rebalancing toward the fair
            # share when new replicas join (they claim what we free).
            # Release in the STORE first — if the store is unreachable
            # the local view stays consistent with it (still held on
            # both sides) instead of locally-dropped-but-store-blocked
            # until TTL.
            self.store.release(shed, self.holder)
            with self._lock:
                self.counters["sheds"] += 1
                self._held.pop(shed, None)
            logger.info(
                "lease manager %s: shed shard %d toward fair share %d",
                self.holder, shed, target,
            )
        # while a peer is starved, claim only up to the floor — claiming
        # to ceil would race the starved peer for the shard we just freed
        claim_target = floor_share if starved else target
        # Yield-to-most-starved: never claim while a live peer holds
        # STRICTLY fewer shards than we do. Without this, tick order
        # decides who wins each freed shard — an under-target incumbent
        # that ticks earlier hoovers every shard the over-target members
        # shed, and a zero-shard newcomer (an autoscale join waiting on
        # its health gate's first-claim condition) starves for as many
        # ticks as the incumbent is below target. The minimum holder is
        # always allowed to claim, so every free shard keeps a claimant
        # and balanced states are untouched.
        min_other = min(
            (count for h, count in holdings.items() if h != self.holder),
            default=None,
        )
        for sid in range(self.store.n_shards):
            with self._lock:
                n_held = len(self._held)
                have = sid in self._held
            if have:
                continue
            if n_held >= claim_target:
                break
            if min_other is not None and n_held > min_other:
                break
            current = self.store.holder_of(sid)
            if current is not None and current != self.holder:
                continue
            # free — or OUR OWN unexpired lease from a previous process
            # incarnation (crash-restart under the same identity, found
            # by the durable-state round): the store renews it at the
            # SAME epoch, so journaled bind intents stay fence-valid
            # across the restart instead of fencing off until TTL
            # expiry re-grants the shard under a bumped epoch.
            lease = self.store.try_acquire(sid, self.holder)
            if lease is not None:
                with self._lock:
                    self.counters["claims"] += 1
                    self._held[sid] = lease
                gained.add(sid)
        if gained:
            logger.info(
                "lease manager %s: claimed shards %s",
                self.holder, sorted(gained),
            )

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"lease-{self.holder}"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.renew_interval_s):
            try:
                self.tick()
            except Exception:
                # a store hiccup must not kill the renewal thread — missing
                # renewals IS the failure mode leases exist to survive
                logger.exception("lease tick failed for %s", self.holder)

    def stop(self, release: bool = True) -> None:
        """Stop renewing. `release=True` (clean shutdown) frees the held
        shards immediately; `release=False` models a crash — shards stay
        leased until TTL expiry, exactly what failover tests need."""
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5)
        if release:
            with self._lock:
                held = list(self._held)
                self._held.clear()
            for sid in held:
                self.store.release(sid, self.holder)
            try:
                self.store.retract_heartbeat(self.holder)
            except LeaseStoreUnavailable:
                pass  # unreachable store: presence ages out via TTL


def assign_initial(
    store: LeaseStore, holders: Iterable[str]
) -> dict[str, list[Lease]]:
    """Deterministic round-robin bootstrap: shard i -> holder i % N.
    Fleet startup uses this so every shard is owned before the first pod
    is observed (manager ticks alone converge, but only after a few
    rounds of fair-share claiming). Returns the acquired leases so the
    holders' managers can adopt them without a second store round-trip
    (2N apiserver calls in a k8s-backed deployment)."""
    holders = list(holders)
    out: dict[str, list[Lease]] = {h: [] for h in holders}
    for sid in range(store.n_shards):
        holder = holders[sid % len(holders)]
        lease = store.try_acquire(sid, holder)
        if lease is not None:
            out[holder].append(lease)
    return out
