"""Disaggregated prefill/decode pools with prepacked admission.

The fleet-scale hazard SARATHI (arXiv:2308.16369) and Prepacking
(arXiv:2404.09529) describe at the engine layer exists at the fleet
layer too: an admission burst is PREFILL-dominated (every new cluster
snapshot pays a fresh cluster-state prefix prefill before any decision
token decodes), and if that burst lands on the same workers serving
latency-critical decode traffic, decode throughput is evicted exactly
when the cluster is busiest. The fleet answer is disaggregation — route
the two phases to distinct worker pools so they never contend:

- **prefill pool**: absorbs admission. The first decisions against a
  NEW cluster snapshot (cold prefix) go here, PREPACKED: concurrent
  short scheduler prompts against one snapshot are batched into a
  single `decide_batch` wire frame (sched/replica.py), and the worker's
  batch surface (LocalLLMBackend.get_scheduling_decisions_batch) hands
  the whole frame to the engine's PACKED CHUNKED admission
  (engine.admit_packed — block-diagonal attention over one packed token
  stream, engine/admission/). The wire-level prepack window and the
  engine-level pack are ONE mechanism: the frame that ships together
  prefills together, with no second whole-prompt prefill wave behind
  the wire batch.
- **decode pool**: serves continuation. Once a snapshot's prefix is
  WARM on the decode pool (the router fires an advisory
  `prewarm_prefix` at the decode pool the moment it first sees a
  snapshot), subsequent decisions against that snapshot are
  decode-dominated (prefix KV hit + a few dozen constrained decision
  tokens) and route here — off the admission pool entirely, so a
  concurrent admission burst cannot evict them.

Classification is by SNAPSHOT, not by pod: the cluster-state prefix is
the prefill cost, and it is keyed by the node snapshot digest — the
same equivalence class the decision cache and the engine's prefix KV
reuse are built on (core/cache._nodes_digest).

Pool roles are enforced at the worker too (`pool_role` on
LocalLLMBackend / StubBackend / ReplicaServer): a decode-role worker
REFUSES admission (`work="prefill"`) frames, so a misconfigured router
surfaces as a loud BackendError instead of silent interference.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import threading
from collections import OrderedDict
from collections.abc import Sequence
from typing import Any

from k8s_llm_scheduler_tpu.core.cache import _nodes_digest
from k8s_llm_scheduler_tpu.engine.backend import (
    BackendError,
    NoFeasibleNodeError,
)
from k8s_llm_scheduler_tpu.observability import spans
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec, SchedulingDecision

logger = logging.getLogger(__name__)

PREFILL = "prefill"
DECODE = "decode"
MIXED = "mixed"
POOL_ROLES = (PREFILL, DECODE, MIXED)


def check_pool_role(role: str, work: str) -> None:
    """The worker-side admission gate. A decode-pool worker refuses
    prefill (admission) work — routing bugs must fail loudly, because
    the silent version of this bug is exactly the decode-eviction
    problem disaggregation exists to prevent. Prefill and mixed roles
    accept everything (a prefill worker finishing a decision decodes
    its few output tokens itself; splitting ONE decision's KV across
    pools is an engine-layer migration this repo does not do)."""
    if role == DECODE and work == PREFILL:
        raise BackendError(
            "pool role 'decode' refuses admission (prefill) work — "
            "route new-snapshot decisions to the prefill pool"
        )


class _SnapshotWarmth:
    """Which snapshot digests are warm on the decode pool. LRU-bounded:
    snapshots churn with every cluster-state change and the router only
    cares about recent ones."""

    def __init__(self, max_entries: int = 64) -> None:
        self._warm: OrderedDict[bytes, bool] = OrderedDict()
        self._max = max_entries
        self._lock = threading.Lock()

    def is_warm(self, digest: bytes) -> bool:
        with self._lock:
            if digest in self._warm:
                self._warm.move_to_end(digest)
                return self._warm[digest]
            return False

    def note(self, digest: bytes, warm: bool) -> bool:
        """Record warmth; returns True iff this digest was NEVER seen
        before (the caller fires the decode-pool prewarm exactly once
        per snapshot)."""
        with self._lock:
            first = digest not in self._warm
            self._warm[digest] = warm or self._warm.get(digest, False)
            self._warm.move_to_end(digest)
            while len(self._warm) > self._max:
                self._warm.popitem(last=False)
            return first

    def mark_warm(self, digest: bytes) -> None:
        self.note(digest, True)


class _PendingPack:
    """One forming admission batch: pods sharing a snapshot, flushed
    together as one decide_batch frame."""

    __slots__ = ("nodes", "pods", "futures", "handle")

    def __init__(self, nodes: Sequence[NodeMetrics]) -> None:
        self.nodes = nodes
        self.pods: list[PodSpec] = []
        self.futures: list[asyncio.Future] = []
        self.handle: asyncio.TimerHandle | None = None


class DisaggregatedBackend:
    """DecisionBackend routing admission to a prefill pool and warm
    continuation to a decode pool, prepacking admission batches.

    Sits at the DecisionBackend seam below DecisionClient (like
    FanoutBackend — members may BE FanoutBackends, ReplicaClients, or
    local backends), so cache/single-flight/breaker/fallback are
    untouched: only leader decisions ever reach the router.

    An empty decode pool degrades to a pure prefill fleet (everything
    routes prefill — the pre-disaggregation behavior). Member choice
    within a pool is least-inflight.
    """

    def __init__(
        self,
        prefill_pool: Sequence[Any],
        decode_pool: Sequence[Any] = (),
        prepack_max_batch: int = 16,
        prepack_window_s: float = 0.002,
    ) -> None:
        if not prefill_pool:
            raise ValueError("DisaggregatedBackend needs a prefill pool")
        self.prefill_pool = list(prefill_pool)
        self.decode_pool = list(decode_pool)
        self.prepack_max_batch = max(1, int(prepack_max_batch))
        self.prepack_window_s = float(prepack_window_s)
        self._warmth = _SnapshotWarmth()
        self._inflight: dict[int, int] = {}  # id(member) -> count
        self._work_sig: dict[tuple[int, str], bool] = {}  # capability memo
        self._lock = threading.Lock()
        # forming packs, keyed by snapshot digest — event-loop-confined
        # (only touched from async paths on the loop thread)
        self._packs: dict[bytes, _PendingPack] = {}
        self.stats_counters = {
            "prefill_routed": 0,
            "decode_routed": 0,
            "packs_flushed": 0,
            "packed_decisions": 0,
            "prewarms_fired": 0,
        }

    # ------------------------------------------------------------ selection
    def _least_loaded(self, pool: list[Any]) -> Any:
        with self._lock:
            return min(pool, key=lambda m: self._inflight.get(id(m), 0))

    def _acquire(self, member: Any) -> None:
        with self._lock:
            self._inflight[id(member)] = self._inflight.get(id(member), 0) + 1

    def _release(self, member: Any) -> None:
        with self._lock:
            self._inflight[id(member)] = max(
                0, self._inflight.get(id(member), 0) - 1
            )

    def _note(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.stats_counters[counter] += n

    # ------------------------------------------------------- classification
    def _classify(self, nodes: Sequence[NodeMetrics]) -> tuple[str, bytes]:
        """prefill | decode for this snapshot. New snapshots are
        admission (cold prefix -> prefill pool) and fire a one-shot
        advisory prewarm at the decode pool; a snapshot routes decode
        only once the decode pool CONFIRMED the install — until then the
        admission burst stays on the prefill pool rather than paying the
        cold prefill twice."""
        digest = _nodes_digest(nodes)
        if not self.decode_pool:
            return PREFILL, digest
        if self._warmth.is_warm(digest):
            return DECODE, digest
        if self._warmth.note(digest, warm=False):
            self._fire_decode_prewarm(digest, nodes)
        return PREFILL, digest

    def _fire_decode_prewarm(
        self, digest: bytes, nodes: Sequence[NodeMetrics]
    ) -> None:
        for member in self.decode_pool:
            fn = getattr(member, "prewarm_prefix", None)
            if fn is None:
                continue
            try:
                fut = fn(nodes)
            except Exception:
                logger.exception("decode-pool prewarm submit failed")
                continue
            if fut is None:
                continue
            self._note("prewarms_fired")

            def _done(f, d=digest) -> None:
                try:
                    ok = bool(f.result())
                except Exception:
                    ok = False
                if ok:
                    self._warmth.mark_warm(d)

            fut.add_done_callback(_done)

    # ------------------------------------------------------------ sync path
    def get_scheduling_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        """Synchronous single-decision path (no prepacking: packing
        needs concurrent arrivals, and a blocking caller has none)."""
        work, _ = self._classify(nodes)
        pool = self.decode_pool if work == DECODE else self.prefill_pool
        member = self._least_loaded(pool)
        self._note(f"{work}_routed")
        self._stamp(work)
        self._acquire(member)
        try:
            return self._member_decide(member, pod, nodes, work)
        finally:
            self._release(member)

    def _accepts_work(self, member: Any, kind: str, fn: Any) -> bool:
        """Signature-inspected ONCE per (member, method) and memoized —
        inspect.signature costs tens of microseconds, which would rival
        the tracing budget if paid per decision. Probed, not try/except
        TypeError (which would re-invoke the member when ITS body raises
        TypeError): does this member understand the work tag?"""
        key = (id(member), kind)
        with self._lock:
            hit = self._work_sig.get(key)
        if hit is None:
            try:
                hit = "work" in inspect.signature(fn).parameters
            except (TypeError, ValueError):
                hit = False
            with self._lock:
                self._work_sig[key] = hit
        return hit

    def _member_decide(
        self, member: Any, pod: PodSpec, nodes: Sequence[NodeMetrics],
        work: str,
    ) -> SchedulingDecision:
        fn = member.get_scheduling_decision
        if self._accepts_work(member, "sync", fn):
            return fn(pod, nodes, work=work)
        return fn(pod, nodes)  # member predates the work tag

    @staticmethod
    def _stamp(work: str) -> None:
        trace = spans.current_trace()
        if trace is not None:
            trace.set_meta(pool=work)

    # ----------------------------------------------------------- async path
    async def get_scheduling_decision_async(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        """The fleet hot path (DecisionClient prefers it). Decode work
        routes immediately; admission parks on a forming pack keyed by
        the snapshot digest — the pack flushes as ONE decide_batch when
        it reaches prepack_max_batch or after prepack_window_s, whichever
        comes first. The window trades ~2 ms of added admission latency
        for one prefill wave instead of N; decode work never waits."""
        work, digest = self._classify(nodes)
        self._note(f"{work}_routed")
        self._stamp(work)
        if work == DECODE:
            member = self._least_loaded(self.decode_pool)
            self._acquire(member)
            try:
                return await self._member_decide_async(
                    member, pod, nodes, work
                )
            finally:
                self._release(member)

        loop = asyncio.get_running_loop()
        pack = self._packs.get(digest)
        if pack is None:
            # equal digests across DIFFERENT snapshot objects (e.g. a
            # TTL refresh on an unchanged cluster) mean identical
            # content — same prompt, safe to join the forming pack;
            # replacing it would abandon the parked futures forever.
            pack = _PendingPack(nodes)
            self._packs[digest] = pack
            pack.handle = loop.call_later(
                self.prepack_window_s, self._flush_pack, digest
            )
        fut: asyncio.Future = loop.create_future()
        pack.pods.append(pod)
        pack.futures.append(fut)
        if len(pack.pods) >= self.prepack_max_batch:
            self._flush_pack(digest)
        return await fut

    async def _member_decide_async(
        self, member: Any, pod: PodSpec, nodes: Sequence[NodeMetrics],
        work: str,
    ) -> SchedulingDecision:
        afn = getattr(member, "get_scheduling_decision_async", None)
        if afn is not None:
            if self._accepts_work(member, "async", afn):
                return await afn(pod, nodes, work=work)
            return await afn(pod, nodes)
        return await asyncio.to_thread(
            self._member_decide, member, pod, nodes, work
        )

    def _flush_pack(self, digest: bytes) -> None:
        """Detach a forming pack and ship it (runs on the event loop —
        call_later callback or the max-batch fast flush)."""
        pack = self._packs.pop(digest, None)
        if pack is None:
            return
        if pack.handle is not None:
            pack.handle.cancel()
        self._note("packs_flushed")
        self._note("packed_decisions", len(pack.pods))
        task = asyncio.ensure_future(self._ship_pack(pack))
        # containment: _ship_pack resolves every future even on member
        # failure; this callback only guards against bugs in _ship_pack
        # itself leaving callers parked forever
        task.add_done_callback(lambda t: self._pack_shipped(t, pack))

    @staticmethod
    def _pack_shipped(task: asyncio.Task, pack: _PendingPack) -> None:
        exc = task.exception() if not task.cancelled() else None
        for fut in pack.futures:
            if not fut.done():
                fut.set_exception(
                    exc if exc is not None
                    else BackendError("prepack shipment dropped its batch")
                )

    async def _ship_pack(self, pack: _PendingPack) -> None:
        member = self._least_loaded(self.prefill_pool)
        self._acquire(member)
        try:
            batch_async = getattr(
                member, "get_scheduling_decisions_batch_async", None
            )
            batch_sync = getattr(
                member, "get_scheduling_decisions_batch", None
            )
            if batch_async is not None:
                results = await batch_async(
                    pack.pods, pack.nodes, work=PREFILL
                )
            elif batch_sync is not None:
                results = await asyncio.to_thread(
                    batch_sync, pack.pods, pack.nodes, PREFILL
                )
            else:
                # member has no batch surface: fan out concurrently so
                # its engine still sees the pack together
                results = await asyncio.gather(
                    *(
                        self._member_decide_async(
                            member, pod, pack.nodes, PREFILL
                        )
                        for pod in pack.pods
                    ),
                    return_exceptions=True,
                )
        except Exception as exc:
            for fut in pack.futures:
                if not fut.done():
                    fut.set_exception(
                        BackendError(f"prepacked admission failed: {exc}")
                    )
            return
        finally:
            self._release(member)
        for fut, result in zip(pack.futures, results):
            if fut.done():
                continue
            if isinstance(result, SchedulingDecision):
                fut.set_result(result)
            elif isinstance(result, BaseException):
                fut.set_exception(result)
            else:
                fut.set_exception(
                    BackendError(f"batch member returned {type(result).__name__}")
                )

    # ------------------------------------------------------------ elasticity
    def occupancy(self) -> dict[str, float]:
        """Mean in-flight work per member, per pool — the autoscale
        controller's pool-pressure signal (fleet/autoscale.py). A pool
        with no members reads 0.0 (nothing to rebalance toward)."""
        with self._lock:
            def mean(pool: list[Any]) -> float:
                if not pool:
                    return 0.0
                total = sum(self._inflight.get(id(m), 0) for m in pool)
                return total / len(pool)

            return {
                "prefill": round(mean(self.prefill_pool), 4),
                "decode": round(mean(self.decode_pool), 4),
            }

    def fleet_geometry(self) -> "FleetGeometry":
        """The roster's slice geometry (engine/sharded/geometry.py): one
        tp-group size per member, prefill-pool members first. Members
        advertise via `slice_tp` or a live engine mesh; unknown = 1."""
        from k8s_llm_scheduler_tpu.engine.sharded import FleetGeometry

        with self._lock:
            roster = [*self.prefill_pool, *self.decode_pool]
        return FleetGeometry.of(roster)

    def split_for_share(self, share: float) -> int:
        """Prefill member count for a target DEVICE share of the fleet.

        The autoscaler steers the split by occupancy share; on a
        heterogeneous fleet a member is not a unit of capacity — a tp=8
        slice is eight chips. This converts the share to device counts
        and snaps to the nearest whole device-group boundary (a split
        can move whole tp groups between pools, never a fraction of
        one), walking the prefill-affinity ordering so the chosen
        prefix is the same set set_split will select."""
        return self.fleet_geometry().split_for_device_share(share)

    def set_split(self, n_prefill: int) -> dict[str, int]:
        """Rebalance the prefill<->decode split over the SAME member
        roster (autoscale output #2). On a heterogeneous fleet the
        roster is ordered by slice geometry first — largest tp groups
        take the prefill slots (prefill is compute-bound and scales
        with group width; decode's small per-step matmuls waste wide
        slices), stable within a size class. A uniform fleet keeps the
        historical stable order (prefill members first, then decode, as
        currently assigned), so in both cases the same `n_prefill`
        always produces the same assignment — membership moves are
        deterministic, not load-timing-chosen.
        `n_prefill` clamps to [1, members] (admission must always have
        somewhere to land; 0 decode members degrades to a pure prefill
        fleet, the pre-disaggregation behavior). Members exposing a
        `pool_role` attribute are retagged so the worker-side admission
        gate (check_pool_role) stays consistent with the router's view.
        In-flight work is untouched: classification is per-decision, so
        the new split applies from the next admission on."""
        from k8s_llm_scheduler_tpu.engine.sharded import FleetGeometry

        with self._lock:
            roster = [*self.prefill_pool, *self.decode_pool]
            geometry = FleetGeometry.of(roster)
            if not geometry.uniform:
                roster = [roster[i] for i in geometry.prefill_order()]
            n_prefill = max(1, min(int(n_prefill), len(roster)))
            new_prefill = roster[:n_prefill]
            new_decode = roster[n_prefill:]
            self.prefill_pool[:] = new_prefill
            self.decode_pool[:] = new_decode
        for member, role in (
            *((m, PREFILL) for m in new_prefill),
            *((m, DECODE) for m in new_decode),
        ):
            if hasattr(member, "pool_role"):
                member.pool_role = role
        return {"prefill": len(new_prefill), "decode": len(new_decode)}

    # ----------------------------------------------------------- advisories
    def prewarm_prefix(self, nodes: Sequence[NodeMetrics]):
        """Scheduler idle-prewarm advisory: forward to the PREFILL pool
        (admission lands there first); the decode pool is prewarmed by
        the router's own per-snapshot advisory. None iff no prefill
        member supports prewarming."""
        futs = []
        for member in self.prefill_pool:
            fn = getattr(member, "prewarm_prefix", None)
            if fn is None:
                continue
            fut = fn(nodes)
            if fut is not None:
                futs.append(fut)
        if not futs:
            return None
        from concurrent.futures import Future

        out: Future = Future()
        state = {"left": len(futs), "ok": True}
        lock = threading.Lock()

        def _done(f) -> None:
            try:
                ok = bool(f.result())
            except Exception:
                ok = False
            with lock:
                state["ok"] &= ok
                state["left"] -= 1
                finished = state["left"] == 0
            if finished and not out.done():
                out.set_result(state["ok"])

        for f in futs:
            f.add_done_callback(_done)
        return out

    def get_stats(self) -> dict:
        with self._lock:
            out: dict[str, Any] = {
                f"pools_{k}": v for k, v in self.stats_counters.items()
            }
        out["pools_prefill_size"] = len(self.prefill_pool)
        out["pools_decode_size"] = len(self.decode_pool)
        first = self.prefill_pool[0]
        if hasattr(first, "get_stats"):
            out.update(first.get_stats())
        return out

    def close(self) -> None:
        for member in (*self.prefill_pool, *self.decode_pool):
            if hasattr(member, "close"):
                member.close()
