"""Closed policy-improvement loop: mine losses -> finetune -> promote.

- learn/miner.py: loss-incident extraction from arena/chaos traces into
  a versioned on-disk incident corpus (per-class counts, provenance).
- learn/curriculum.py: deterministic reconstruction of incident decision
  states + replay-mixed finetune batches over train/distill machinery.
- learn/loop.py: the LearnLoop controller driving mine -> finetune ->
  registry publish -> two-sided gate (mined-weakness improvement + base
  arena tolerance) -> hot-swap promotion, with a byte-replayable trace.

Surfaces: `cli learn mine/build/run/status/replay` and
`bench.py --preset learn`.
"""

from k8s_llm_scheduler_tpu.learn.curriculum import (
    curriculum_batches,
    curriculum_summary,
    incident_cases,
    reconstruct_cases,
)
from k8s_llm_scheduler_tpu.learn.loop import (
    LearnConfig,
    LearnError,
    LearnLoop,
    backend_decide,
    build_learn_trace,
    finetune_on_corpus,
    load_learn_trace,
    replay_learn_trace,
    save_learn_trace,
    verify_learn_trace,
    weakness_report,
)
from k8s_llm_scheduler_tpu.learn.miner import (
    CorpusError,
    IncidentCorpus,
    corpus_digest,
    decide_policy_arm,
    mine_arena_report,
    mine_chaos_report,
    mine_placements,
    mine_scenario,
    per_class_counts,
)

__all__ = [
    "CorpusError",
    "IncidentCorpus",
    "LearnConfig",
    "LearnError",
    "LearnLoop",
    "backend_decide",
    "build_learn_trace",
    "corpus_digest",
    "curriculum_batches",
    "curriculum_summary",
    "decide_policy_arm",
    "finetune_on_corpus",
    "incident_cases",
    "load_learn_trace",
    "mine_arena_report",
    "mine_chaos_report",
    "mine_placements",
    "mine_scenario",
    "per_class_counts",
    "reconstruct_cases",
    "replay_learn_trace",
    "save_learn_trace",
    "verify_learn_trace",
    "weakness_report",
]
