"""Curriculum: mined incidents -> deterministic finetune batches.

The bridge between the incident corpus (learn/miner.py) and the train
step (train/train_step.py). Three responsibilities:

1. **Case reconstruction** (`reconstruct_cases`): an incident records
   only (scenario spec, wave, pod name) — the scenario regenerates from
   its seed and the decision STATE the pod was judged in replays
   deterministically: the reference trajectory is the spread-lookahead
   teacher replayed exactly as the arena's policy runner replays it
   (sim/arena._run_policy_arm discipline — one snapshot per wave, all of
   a wave's decisions against it, placements folded after). The corpus
   therefore ships kilobytes of provenance, not serialized tensors, and
   two machines reconstruct bit-identical training cases.

2. **Supervision** rides the established distillation machinery: each
   reconstructed case goes through train/distill.case_to_pair — the SAME
   teacher (`resource_balanced`), answer format, name-span weighting,
   and CoT scratchpad path the bootstrap corpus uses. The lookahead
   teacher is the *detector* (it finds where the policy loses); the
   computable heuristic remains the *supervisor* (it is what the runtime
   can actually distill and what the weakness gate scores against).

3. **Replay mixing** (`curriculum_batches`): each batch row draws mined
   hard cases with probability (1 - replay_fraction) and the base
   training distribution (train/distill.random_cases) otherwise — the
   anti-catastrophic-forgetting knob. Pinned behavior: replay_fraction
   1.0 degenerates to pure base-distribution batches, 0.0 to pure
   incident batches, and the row order is a pure function of the seed
   (the learn loop's "deterministic batch order" contract).
"""

from __future__ import annotations

import logging
from typing import Iterator, Sequence

import numpy as np

from k8s_llm_scheduler_tpu.sim.scenarios import (
    ClusterModel,
    ScenarioSpec,
    generate_scenario,
)
from k8s_llm_scheduler_tpu.sim.teacher import SpreadLookaheadTeacher
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec

logger = logging.getLogger(__name__)


def reconstruct_cases(
    spec_dict: dict,
    wanted: dict[str, str],
) -> list[tuple[PodSpec, list[NodeMetrics], str]]:
    """Rebuild the (pod, snapshot) decision states for `wanted`
    ({pod name -> scenario class}) by replaying the reference trajectory.

    Mirrors sim/arena._run_policy_arm exactly: churn applies before the
    wave, ONE snapshot serves the whole wave, the teacher's own
    placements fold in after the wave — so the state a mined pod is
    reconstructed in is the state the reference decided it in, every
    time, on every machine."""
    scenario = generate_scenario(ScenarioSpec.from_dict(spec_dict))
    teacher = SpreadLookaheadTeacher()
    teacher.reset()
    model = ClusterModel(scenario)
    out: list[tuple[PodSpec, list[NodeMetrics], str]] = []
    remaining = dict(wanted)
    for wave_idx, wave in enumerate(scenario.waves):
        model.apply_churn(scenario.churn_for_wave(wave_idx))
        if not wave:
            continue
        snapshot = model.metrics()
        teacher.begin_wave()
        decided: list[tuple] = []
        for pod in wave:
            spec = pod.to_pod_spec()
            if pod.name in remaining:
                out.append((spec, snapshot, remaining.pop(pod.name)))
            node = teacher.decide(spec, snapshot)
            if node is not None:
                decided.append((pod, node))
        for pod, node in decided:
            model.place(pod, node)
        if not remaining:
            break
    if remaining:
        raise ValueError(
            f"incident pods not in scenario {spec_dict.get('name')!r}: "
            f"{sorted(remaining)[:5]}"
        )
    return out


def incident_cases(
    record: dict,
) -> list[tuple[PodSpec, list[NodeMetrics], str]]:
    """Every corpus version's incidents as reconstructed cases, in the
    corpus's own deterministic order (sources in recorded order,
    incidents in their sorted order)."""
    out: list[tuple[PodSpec, list[NodeMetrics], str]] = []
    for source in record["sources"]:
        wanted = {
            inc["pod"]: inc["kind"] for inc in source["incidents"]
        }
        if wanted:
            out.extend(reconstruct_cases(source["scenario_spec"], wanted))
    return out


def curriculum_summary(
    record: dict,
    replay_fraction: float,
    cases: "Sequence[tuple] | None" = None,
) -> dict:
    """What `cli learn build` prints: reconstructable rows per class plus
    the mix the batches will draw. `cases` lets a caller that already
    reconstructed the corpus (the learn loop does it once per cycle)
    skip the scenario regen + teacher replay."""
    if cases is None:
        cases = incident_cases(record)
    per_class: dict[str, int] = {}
    for _pod, _nodes, kind in cases:
        per_class[kind] = per_class.get(kind, 0) + 1
    return {
        "corpus_version": record["version"],
        "corpus_digest": record["digest"],
        "incident_cases": len(cases),
        "per_class": dict(sorted(per_class.items())),
        "replay_fraction": replay_fraction,
        "incident_fraction": round(1.0 - replay_fraction, 6),
    }


def curriculum_batches(
    tokenizer,
    record: dict,
    *,
    batch_size: int,
    seq_len: int,
    replay_fraction: float = 0.3,
    seed: int = 0,
    n_nodes: int = 5,
    answer_style: str = "direct",
    name_weight: float = 8.0,
    cot_weight: float = 1.0,
    cases: "Sequence[tuple] | None" = None,
) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Endless batched (tokens, seq_lens, answer_starts, loss_weights)
    mixing reconstructed incident cases with base-distribution replay.
    `cases` (pre-reconstructed incident cases) skips the per-call
    reconstruction for callers that already hold them.

    Deterministic: the mix decisions, the incident epoch shuffles, and
    the replay stream all derive from `seed` alone, so two runs of the
    same (corpus version, seed) train on identical batches in identical
    order — the property the learn loop's seeded-finetune contract and
    its byte-compared trace lean on."""
    from k8s_llm_scheduler_tpu.core.prompt import PromptEngine
    from k8s_llm_scheduler_tpu.train.distill import (
        case_to_pair,
        clip_row,
        random_cases,
    )

    if not 0.0 <= replay_fraction <= 1.0:
        raise ValueError(
            f"replay_fraction must be in [0, 1], got {replay_fraction}"
        )
    hard = list(cases) if cases is not None else incident_cases(record)
    pe = PromptEngine()
    if replay_fraction < 1.0:
        # liveness: keep only incident cases the supervisor can actually
        # supervise (case_to_pair abstains when fallback_decision finds
        # no feasible node). With replay_fraction 0.0 an all-abstain
        # corpus would otherwise redraw forever inside the batch loop —
        # raise up front instead of hanging the finetune stage.
        hard = [
            case for case in hard
            if case_to_pair(
                tokenizer, pe, case[0], case[1],
                answer_style=answer_style,
                name_weight=name_weight, cot_weight=cot_weight,
            ) is not None
        ]
        if not hard:
            raise ValueError(
                f"corpus v{record.get('version')} has no supervisable "
                "incident cases (teacher abstains on every reconstructed "
                "state) — nothing to finetune on"
            )
    mix_rng = np.random.default_rng(seed)
    epoch_rng = np.random.default_rng(seed + 1)
    replay = random_cases(n_nodes=n_nodes, seed=seed + 17)

    def hard_stream():
        while True:
            order = epoch_rng.permutation(len(hard))
            for i in order:
                yield hard[int(i)][:2]

    hard_it = hard_stream() if hard else None
    warned = False
    pad = tokenizer.pad_id
    while True:
        tokens = np.full((batch_size, seq_len), pad, dtype=np.int32)
        lens = np.zeros(batch_size, dtype=np.int32)
        starts = np.zeros(batch_size, dtype=np.int32)
        weights = np.ones((batch_size, seq_len), dtype=np.float32)
        b = 0
        while b < batch_size:
            use_replay = (
                hard_it is None or mix_rng.random() < replay_fraction
            )
            pod, nodes = next(replay if use_replay else hard_it)
            pair = case_to_pair(
                tokenizer, pe, pod, nodes,
                answer_style=answer_style,
                name_weight=name_weight, cot_weight=cot_weight,
            )
            if pair is None:
                continue  # teacher abstained: redraw (deterministically)
            ids, ans_start, _span, w_ids = pair
            ids, ans_start, w_ids, clipped = clip_row(
                ids, ans_start, w_ids, seq_len
            )
            if clipped and not warned:
                logger.warning(
                    "curriculum rows exceed seq_len=%d; truncating prompt "
                    "context from the left (answers preserved)", seq_len,
                )
                warned = True
            tokens[b, : len(ids)] = ids
            lens[b] = len(ids)
            starts[b] = ans_start
            weights[b, : len(ids)] = w_ids
            b += 1
        yield tokens, lens, starts, weights
