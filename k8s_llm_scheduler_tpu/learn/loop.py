"""LearnLoop: mine -> finetune -> publish -> gate -> promote, as one unit.

The capstone controller over five existing planes. Every stage already
existed in-tree — the sim arena finds waves where the policy loses to
the spread-lookahead reference, train/distill.py turns teacher decisions
into weighted training pairs, train/train_step.py finetunes, rollout/
gates/swaps/rolls back — and this module is the missing spine that makes
"sim finds a weakness -> policy improves -> canary promotes" ONE seeded,
replayable operation instead of a human copy-pasting between five CLIs.

One `run_cycle` is:

1. **mine** (learn/miner.py): seeded scenarios run the incumbent against
   the teacher; loss incidents land in the versioned incident corpus,
   lineage pointing at the incumbent's registry version.
2. **build** (learn/curriculum.py): incidents reconstruct into training
   cases, mixed with base-distribution replay at `replay_fraction`.
3. **finetune**: TrainState + causal_lm_loss over the curriculum batches
   (seeded init, deterministic batch order), starting FROM the incumbent
   checkpoint so the candidate is an increment, not a reroll.
4. **publish**: the candidate enters the rollout registry with lineage
   (parent = incumbent, scores carry the corpus version + digest).
5. **gate**, two-sided: the candidate must STRICTLY beat the incumbent
   on the mined-weakness cases (`weakness_report` — the very cases the
   corpus says the incumbent lost), AND stay within tolerance on the
   base arena (`rollout/canary.run_gate` — the catastrophic-forgetting
   backstop the replay fraction exists to make passable).
6. **promote**: staggered/quiesced hot swap through the provided
   swapper on pass; rejected-version memory on fail (a failed candidate
   is never re-gated every cycle).

The deterministic record of a cycle is its learn TRACE (sim/trace.py
discipline): the mined sources, the corpus digest, every weakness-case
decision, and the gate placements — everything timing-free. Replay
re-derives the incidents, scores, checks, and action from the recorded
decisions alone (no model re-run) and must byte-compare.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Any, Callable, Sequence

from k8s_llm_scheduler_tpu.learn.curriculum import (
    curriculum_batches,
    curriculum_summary,
    incident_cases,
)
from k8s_llm_scheduler_tpu.learn.miner import (
    IncidentCorpus,
    corpus_digest,
    mine_placements,
    mine_scenario,
    per_class_counts,
    source_digest,
)
from k8s_llm_scheduler_tpu.observability import spans
from k8s_llm_scheduler_tpu.rollout.canary import GateConfig
from k8s_llm_scheduler_tpu.sim.scenarios import ScenarioSpec
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec

logger = logging.getLogger(__name__)

LEARN_TRACE_VERSION = 1

DecideFn = Callable[[PodSpec, Sequence[NodeMetrics]], "str | None"]


class LearnError(RuntimeError):
    pass


# ------------------------------------------------------------------ config
@dataclasses.dataclass
class LearnConfig:
    """One cycle's knobs, all seed-derived where it matters."""

    seed: int = 0
    # mining: one arena scenario per seed, covering the shared taxonomy
    mine_seeds: tuple[int, ...] = (0, 1)
    mine_nodes: int = 8
    mine_pods: int = 48
    mine_shapes: int = 8
    mine_waves: int = 3
    constraint_mix: tuple[str, ...] = (
        "uniform", "selector", "tainted", "affinity"
    )
    taint_frac: float = 0.2
    spread_margin: float = 0.005
    # curriculum / finetune
    replay_fraction: float = 0.3
    steps: int = 200
    batch_size: int = 4
    seq_len: int = 1024
    lr: float = 3e-4
    # weakness gate: candidate must beat incumbent by MORE than margin on
    # the mined cases (strict — a tie is not an improvement)
    weakness_cases: int = 32
    weakness_margin: float = 0.0
    # base-arena tolerance gate (rollout/canary.run_gate)
    gate: GateConfig = dataclasses.field(default_factory=GateConfig)
    # registry retention after a cycle (0 = keep all); the retention walk
    # receives the loop's pinned set (open candidate + corpus lineage)
    retain: int = 0

    def mine_specs(self) -> list[ScenarioSpec]:
        return [
            ScenarioSpec(
                name=f"learn-mine-{seed}",
                seed=int(seed),
                n_nodes=self.mine_nodes,
                n_pods=self.mine_pods,
                shapes=self.mine_shapes,
                arrival="waves",
                n_waves=self.mine_waves,
                hetero=True,
                taint_frac=self.taint_frac,
                constraint_mix=tuple(self.constraint_mix),
            )
            for seed in self.mine_seeds
        ]


# --------------------------------------------------------------- weakness
def backend_decide(backend) -> DecideFn:
    """A DecisionBackend as a bare decide function (the train/eval shape):
    backend errors and infeasibility read as abstention, exactly as
    evaluate_checkpoint scores them."""
    from k8s_llm_scheduler_tpu.engine.backend import (
        BackendError,
        NoFeasibleNodeError,
    )

    def decide(pod: PodSpec, nodes: Sequence[NodeMetrics]) -> str | None:
        try:
            return backend.get_scheduling_decision(pod, nodes).selected_node
        except (NoFeasibleNodeError, BackendError):
            return None

    return decide


def _score_decisions(
    cases: Sequence[tuple[PodSpec, list[NodeMetrics], str]],
    decisions: Sequence[dict],
) -> dict:
    """THE one weakness scorer, shared by the live gate (weakness_report)
    and trace replay (_score_recorded_weakness): agreement with the
    distillation SUPERVISOR (core/fallback resource_balanced — what the
    curriculum trains toward) over (case, recorded decision) pairs. A
    scoring-rule change made in only one consumer would make replays
    diverge from every recorded trace, so there is only one body."""
    from k8s_llm_scheduler_tpu.train.eval import teacher_decide

    per_class: dict[str, dict[str, int]] = {}
    agree = total = 0
    for rec in decisions:
        pod, nodes, kind = cases[int(rec["idx"])]
        got = rec["got"]
        target = teacher_decide(pod, nodes)
        if target is None:
            continue
        total += 1
        bucket = per_class.setdefault(kind, {"n": 0, "agree": 0})
        bucket["n"] += 1
        valid = got is not None and got in {n.name for n in nodes}
        if valid and got == target:
            agree += 1
            bucket["agree"] += 1
    return {
        "n_cases": total,
        "score": round(agree / total, 6) if total else 0.0,
        "per_class": {k: dict(v) for k, v in sorted(per_class.items())},
        "decisions": list(decisions),
    }


def weakness_report(
    decide: DecideFn,
    cases: Sequence[tuple[PodSpec, list[NodeMetrics], str]],
) -> dict:
    """Run `decide` over the mined-weakness cases and score it against
    the supervisor teacher (see _score_decisions)."""
    decisions = [
        {"idx": idx, "pod": pod.name, "kind": kind,
         "got": decide(pod, nodes)}
        for idx, (pod, nodes, kind) in enumerate(cases)
    ]
    return _score_decisions(cases, decisions)


def _score_recorded_weakness(
    cases: Sequence[tuple[PodSpec, list[NodeMetrics], str]],
    decisions: Sequence[dict],
) -> dict:
    """Rescore RECORDED decisions (trace replay: no model re-run — the
    sim/trace discipline of re-deriving everything derivable from
    recorded choices), after validating they align with the
    reconstructed cases."""
    checked: list[dict] = []
    for rec in decisions:
        idx = int(rec["idx"])
        if idx >= len(cases):
            raise LearnError(
                f"recorded weakness case idx {idx} exceeds reconstructed "
                f"case count {len(cases)}"
            )
        pod, _nodes, kind = cases[idx]
        if pod.name != rec["pod"] or kind != rec["kind"]:
            raise LearnError(
                f"recorded weakness case {idx} ({rec['pod']}/{rec['kind']}) "
                f"does not match reconstruction ({pod.name}/{kind})"
            )
        checked.append(
            {"idx": idx, "pod": pod.name, "kind": kind, "got": rec["got"]}
        )
    return _score_decisions(cases, checked)


# --------------------------------------------------------------- finetune
def finetune_on_corpus(
    model_cfg,
    tokenizer_name: str,
    record: dict,
    out_dir: str,
    *,
    base_checkpoint: str | None = None,
    steps: int = 200,
    batch_size: int = 4,
    seq_len: int = 1024,
    lr: float = 3e-4,
    replay_fraction: float = 0.3,
    seed: int = 0,
    answer_style: str = "direct",
    mesh_axes: dict | None = None,
    log_every: int = 25,
    cases: "Sequence[tuple] | None" = None,
) -> float:
    """The loop's default trainer: TrainState + causal_lm_loss over the
    corpus curriculum, seeded init, deterministic batch order, starting
    from `base_checkpoint` (the incumbent) when given. Saves an orbax
    checkpoint to `out_dir`; returns the final loss. `cases` forwards
    pre-reconstructed incident cases to the curriculum (the loop
    reconstructs once per cycle)."""
    import jax
    import optax

    from k8s_llm_scheduler_tpu.engine.tokenizer import build_builtin_tokenizer
    from k8s_llm_scheduler_tpu.models.loader import (
        restore_checkpoint,
        save_checkpoint,
    )
    from k8s_llm_scheduler_tpu.parallel.mesh import mesh_from_config
    from k8s_llm_scheduler_tpu.train.train_step import make_train_step

    tokenizer, cfg = build_builtin_tokenizer(tokenizer_name, model_cfg)
    mesh = mesh_from_config(mesh_axes)
    init_fn, step_fn = make_train_step(
        cfg, mesh, optimizer=optax.adamw(lr)
    )
    state = init_fn(jax.random.PRNGKey(seed))
    if base_checkpoint is not None:
        params = restore_checkpoint(
            base_checkpoint, cfg,
            mesh if mesh.devices.size > 1 else None,
            tp="tp" if mesh.shape.get("tp", 1) > 1 else None,
            fsdp="fsdp" if mesh.shape.get("fsdp", 1) > 1 else None,
        )
        state = state._replace(params=params)
    batches = curriculum_batches(
        tokenizer, record,
        batch_size=batch_size, seq_len=seq_len,
        replay_fraction=replay_fraction, seed=seed,
        answer_style=answer_style, cases=cases,
    )
    loss = float("nan")
    for step in range(1, steps + 1):
        tokens, lens, starts, weights = next(batches)
        tokens, lens, starts, weights = step_fn.place_batch(
            tokens, lens, starts, weights
        )
        state, loss_arr = step_fn(state, tokens, lens, starts, weights)
        if step % log_every == 0 or step == steps:
            loss = float(loss_arr)
            logger.info(
                "learn finetune step %d/%d loss %.4f", step, steps, loss
            )
    save_checkpoint(out_dir, state.params)
    return loss


# -------------------------------------------------------------------- loop
class LearnLoop:
    """The closed policy-improvement controller.

    Pluggable seams so the cycle logic is testable without a model (and
    so `bench.py --preset learn` / `cli learn run` can drive the real
    micro engine through the identical code path):

    - `mine_arm_factory() -> sim.ArmSpec`: the incumbent as an arena arm
      (stack arm for the production surface, policy arm for cheap runs);
    - `incumbent_decide_factory() -> (DecideFn, close)`: the incumbent
      as a bare decide function for the weakness gate;
    - `candidate_decide_factory(ckpt_dir) -> (DecideFn, close)`: same,
      for the freshly trained candidate;
    - `train_fn(record, out_dir) -> loss`: the finetune stage (default:
      finetune_on_corpus from the incumbent checkpoint — requires
      model_cfg + tokenizer_name);
    - `gate_runner(version) -> run_gate verdict`: the base-arena
      tolerance gate;
    - `swapper.swap_to(version)`: optional live promotion (HotSwapper or
      rollout/canary.staggered_swap wrapper); without one the cycle just
      moves the registry's active pointer.
    """

    def __init__(
        self,
        registry,
        corpus: IncidentCorpus,
        config: LearnConfig | None = None,
        *,
        mine_arm_factory: Callable[[], Any],
        incumbent_decide_factory: Callable[[], tuple[DecideFn, Callable]],
        candidate_decide_factory: Callable[[str], tuple[DecideFn, Callable]],
        gate_runner: Callable[[int], dict],
        train_fn: Callable[[dict, str], float] | None = None,
        model_cfg: Any = None,
        tokenizer_name: str = "byte",
        answer_style: str = "direct",
        mesh_axes: dict | None = None,
        swapper: Any = None,
    ) -> None:
        self.registry = registry
        self.corpus = corpus
        self.config = config or LearnConfig()
        self.mine_arm_factory = mine_arm_factory
        self.incumbent_decide_factory = incumbent_decide_factory
        self.candidate_decide_factory = candidate_decide_factory
        self.gate_runner = gate_runner
        self.train_fn = train_fn
        self.model_cfg = model_cfg
        self.tokenizer_name = tokenizer_name
        self.answer_style = answer_style
        self.mesh_axes = mesh_axes
        self.swapper = swapper
        if train_fn is None and model_cfg is None:
            raise ValueError(
                "LearnLoop needs either train_fn or model_cfg (+ tokenizer) "
                "for the default finetune stage"
            )
        self.rejected: set[int] = set()
        self._open_candidate: int | None = None
        # (corpus version, reconstructed cases) memo for the current cycle
        self._cycle_cases: tuple | None = None
        # incumbent checkpoint path captured at mine time (see
        # _default_train)
        self._cycle_base_ckpt: str | None = None
        self.counters = {
            "cycles": 0,
            "incidents_mined": 0,
            "weakness_pass": 0,
            "weakness_fail": 0,
            "gate_pass": 0,
            "gate_fail": 0,
            "promotions": 0,
            "rejections": 0,
        }
        self.last_cycle: dict | None = None

    # ------------------------------------------------------------- stages
    def mine_sources(self) -> list[dict]:
        return [
            mine_scenario(
                spec, self.mine_arm_factory(),
                spread_margin=self.config.spread_margin,
                wave_timeout_s=self.config.gate.wave_timeout_s,
            )
            for spec in self.config.mine_specs()
        ]

    def _weakness_cases(self, record: dict):
        return self._cases_for(record)[: self.config.weakness_cases]

    def _cases_for(self, record: dict):
        """Reconstruct the corpus's incident cases ONCE per cycle (the
        build, finetune, and gate stages all consume the same list —
        re-replaying the teacher trajectory three times per cycle is
        pure waste)."""
        if (
            self._cycle_cases is None
            or self._cycle_cases[0] != record.get("version")
        ):
            self._cycle_cases = (
                record.get("version"), incident_cases(record)
            )
        return self._cycle_cases[1]

    def _default_train(self, record: dict, out_dir: str) -> float:
        # finetune from the incumbent CAPTURED AT MINE TIME, never a
        # re-read of the active pointer: a promotion landing mid-cycle
        # (another loop, `cli rollout promote`) must not make the
        # candidate's lineage point at a checkpoint that never produced
        # the mined placements
        base = self._cycle_base_ckpt
        cfg = self.config
        return finetune_on_corpus(
            self.model_cfg, self.tokenizer_name, record, out_dir,
            base_checkpoint=base,
            steps=cfg.steps, batch_size=cfg.batch_size,
            seq_len=cfg.seq_len, lr=cfg.lr,
            replay_fraction=cfg.replay_fraction, seed=cfg.seed,
            answer_style=self.answer_style, mesh_axes=self.mesh_axes,
            cases=self._cases_for(record),
        )

    def pinned_versions(self) -> set[int]:
        """Registry versions the retention walk must never evict: the
        candidate currently mid-cycle (published but not yet judged) and
        every checkpoint any incident-corpus version mined against
        (rollout/registry.retain pinned set — the eviction bug this PR
        fixes)."""
        pinned = set(self.corpus.lineage_versions())
        if self._open_candidate is not None:
            pinned.add(self._open_candidate)
        return pinned

    # -------------------------------------------------------------- cycle
    def run_cycle(self, work_dir: str | Path, note: str = "") -> dict:
        """One full mine -> finetune -> publish -> gate -> promote pass.

        Returns the cycle report; the deterministic trace payload rides
        under "_trace" (build_learn_trace extracts it; timing and loss
        stay outside it, like the arena's report/trace split)."""
        cfg = self.config
        work_dir = Path(work_dir)
        work_dir.mkdir(parents=True, exist_ok=True)
        out_dir = str(work_dir / "candidate")
        report: dict[str, Any] = {"seed": cfg.seed}
        self.counters["cycles"] += 1
        with spans.start_trace("learn_cycle"):
            with spans.span("learn.mine") as sp:
                incumbent_version = self.registry.active()
                self._cycle_base_ckpt = (
                    str(self.registry.get(incumbent_version).checkpoint_path)
                    if incumbent_version is not None
                    else None
                )
                sources = self.mine_sources()
                record = self.corpus.add_version(
                    sources,
                    checkpoint_version=incumbent_version,
                    note=note or f"learn cycle {self.counters['cycles']}",
                )
                self.counters["incidents_mined"] += record["n_incidents"]
                if sp is not None:
                    sp.attrs.update(
                        incidents=record["n_incidents"],
                        corpus_version=record["version"],
                    )
            report["corpus_version"] = record["version"]
            report["corpus_digest"] = record["digest"]
            report["per_class"] = record["per_class"]

            with spans.span("learn.build"):
                report["curriculum"] = curriculum_summary(
                    record, cfg.replay_fraction,
                    cases=self._cases_for(record),
                )

            with spans.span("learn.finetune"):
                train = self.train_fn or self._default_train
                report["train_loss"] = train(record, out_dir)

            with spans.span("learn.publish"):
                manifest = self.registry.publish(
                    out_dir,
                    cfg=self.model_cfg,
                    tokenizer=self.tokenizer_name,
                    parent=incumbent_version,
                    scores={"learn": {
                        "corpus_version": record["version"],
                        "corpus_digest": record["digest"],
                        "per_class": record["per_class"],
                    }},
                    note=note or "learn loop candidate",
                )
                version = manifest.version
                self._open_candidate = version
            report["candidate_version"] = version
            report["incumbent_version"] = incumbent_version

            try:
                with spans.span("learn.gate") as sp:
                    weakness, gate = self._gate(record, out_dir, version)
                    if sp is not None:
                        sp.attrs.update(
                            weakness_pass=weakness["pass"],
                            gate_pass=gate["pass"],
                        )
                report["weakness"] = {
                    k: weakness[k]
                    for k in ("incumbent", "candidate", "margin", "pass")
                }
                report["gate"] = {
                    "pass": gate["pass"], "checks": gate["checks"],
                }
                promoted = weakness["pass"] and gate["pass"]
                with spans.span("learn.swap") as sp:
                    if promoted:
                        if self.swapper is not None:
                            report["swap"] = self.swapper.swap_to(version)
                        self.registry.set_active(version)
                        self.counters["promotions"] += 1
                        report["action"] = "promoted"
                    else:
                        # rejected-version memory: this candidate is never
                        # re-gated; the next cycle mines + trains afresh
                        self.rejected.add(version)
                        self.counters["rejections"] += 1
                        report["action"] = "rejected"
                    if sp is not None:
                        sp.attrs.update(action=report["action"])
                self.registry.record_scores(version, {"learn_gate": {
                    "weakness": {
                        "incumbent": weakness["incumbent"]["score"],
                        "candidate": weakness["candidate"]["score"],
                        "pass": weakness["pass"],
                    },
                    "base": {"pass": gate["pass"], "checks": gate["checks"]},
                    "action": report["action"],
                }})
            finally:
                self._open_candidate = (
                    version if report.get("action") is None else None
                )

        if cfg.retain:
            self.registry.retain(cfg.retain, pinned=self.pinned_versions())

        report["_trace"] = self._build_trace(
            sources, record, weakness, gate, report["action"]
        )
        logger.info(
            "learn cycle %d: %s candidate v%d (weakness %.3f -> %.3f, "
            "base gate %s)",
            self.counters["cycles"], report["action"], version,
            weakness["incumbent"]["score"], weakness["candidate"]["score"],
            gate["pass"],
        )
        self.last_cycle = {
            k: report[k]
            for k in (
                "action", "candidate_version", "corpus_version", "per_class",
            )
        }
        return report

    def _gate(self, record: dict, out_dir: str, version: int):
        cfg = self.config
        cases = self._weakness_cases(record)
        if not cases:
            raise LearnError("weakness gate has zero reconstructable cases")
        inc_decide, inc_close = self.incumbent_decide_factory()
        try:
            incumbent = weakness_report(inc_decide, cases)
        finally:
            inc_close()
        if incumbent["n_cases"] == 0:
            # the supervisor abstained on every reconstructed case: the
            # gate would be vacuous (0.0 vs 0.0 rejects forever) —
            # refuse loudly instead of burning a finetune per cycle
            raise LearnError(
                "weakness gate scored zero cases (supervisor teacher "
                "abstained on every mined state)"
            )
        cand_decide, cand_close = self.candidate_decide_factory(out_dir)
        try:
            candidate = weakness_report(cand_decide, cases)
        finally:
            cand_close()
        weakness = {
            "incumbent": incumbent,
            "candidate": candidate,
            "margin": cfg.weakness_margin,
            "pass": candidate["score"] > incumbent["score"]
            + cfg.weakness_margin,
        }
        self.counters[
            "weakness_pass" if weakness["pass"] else "weakness_fail"
        ] += 1
        gate = dict(self.gate_runner(version))
        self.counters["gate_pass" if gate["pass"] else "gate_fail"] += 1
        return weakness, gate

    # -------------------------------------------------------------- trace
    def _build_trace(
        self, sources, record, weakness, gate, action
    ) -> dict:
        gcfg = self.config.gate
        return {
            "version": LEARN_TRACE_VERSION,
            "seed": self.config.seed,
            "mine": {
                "sources": [_trace_source(s) for s in sources],
                "per_class": record["per_class"],
                "corpus_digest": record["digest"],
            },
            "weakness": {
                "margin": self.config.weakness_margin,
                "incumbent": _trace_weakness(weakness["incumbent"]),
                "candidate": _trace_weakness(weakness["candidate"]),
                "pass": weakness["pass"],
            },
            "gate": {
                "scenario_spec": gate["scenario_spec"],
                "config": {
                    "spread_tolerance": gcfg.spread_tolerance,
                    "constraint_tolerance": gcfg.constraint_tolerance,
                    "bound_tolerance": gcfg.bound_tolerance,
                },
                "incumbent": gate["traces"]["incumbent"],
                "candidate": gate["traces"]["candidate"],
                "checks": gate["checks"],
                "pass": gate["pass"],
            },
            "action": action,
        }

    def stats(self) -> dict:
        out = {
            **self.counters,
            "active_version": self.registry.active(),
            "rejected": sorted(self.rejected),
            "corpus_versions": len(self.corpus.versions()),
        }
        if self.last_cycle is not None:
            out["last_cycle"] = dict(self.last_cycle)
        return out


def _trace_source(source: dict) -> dict:
    keys = (
        "scenario_spec", "arm", "reference", "placements", "unschedulable",
        "ref_placements", "ref_unschedulable", "spread_margin", "incidents",
        "trace_digest",
    )
    return {k: source[k] for k in keys}


def _trace_weakness(side: dict) -> dict:
    return {
        "score": side["score"],
        "n_cases": side["n_cases"],
        "per_class": side["per_class"],
        "decisions": side["decisions"],
    }


# ------------------------------------------------------------ trace replay
def build_learn_trace(report: dict) -> dict:
    return report["_trace"]


def save_learn_trace(report: dict, path) -> bytes:
    from k8s_llm_scheduler_tpu.sim.trace import canonical_bytes

    data = canonical_bytes(build_learn_trace(report))
    Path(path).write_bytes(data)
    return data


def load_learn_trace(path) -> dict:
    return json.loads(Path(path).read_bytes().decode("utf-8"))


def replay_learn_trace(trace: dict) -> dict:
    """Re-derive everything derivable from the recorded trace: incidents
    from the recorded placements, the corpus digest from the re-mined
    sources, weakness scores from the recorded per-case decisions, gate
    scores from the recorded gate placements, checks and the action from
    the recorded tolerances. Returns a NEW trace whose canonical bytes
    must equal the recorded ones — no model, no training re-run."""
    from k8s_llm_scheduler_tpu.sim.arena import score_placement
    from k8s_llm_scheduler_tpu.sim.scenarios import generate_scenario

    if trace.get("version") != LEARN_TRACE_VERSION:
        raise LearnError(
            f"learn trace version {trace.get('version')!r} != "
            f"{LEARN_TRACE_VERSION}"
        )
    # ---- mine: re-derive incidents + digests from recorded placements
    sources_out = []
    for rec in trace["mine"]["sources"]:
        spec = ScenarioSpec.from_dict(rec["scenario_spec"])
        scenario = generate_scenario(spec)
        pod_names = {p.name for wave in scenario.waves for p in wave}
        unknown = (
            set(rec["placements"]) | set(rec["ref_placements"])
        ) - pod_names
        if unknown:
            raise LearnError(
                f"trace places pods the scenario never generated: "
                f"{sorted(unknown)[:5]}"
            )
        source = {
            "scenario_spec": spec.to_dict(),
            "arm": rec["arm"],
            "reference": rec["reference"],
            "placements": dict(sorted(rec["placements"].items())),
            "unschedulable": sorted(rec["unschedulable"]),
            "ref_placements": dict(sorted(rec["ref_placements"].items())),
            "ref_unschedulable": sorted(rec["ref_unschedulable"]),
            "spread_margin": rec["spread_margin"],
        }
        source["incidents"] = mine_placements(
            scenario,
            source["placements"], source["unschedulable"],
            source["ref_placements"], source["ref_unschedulable"],
            spread_margin=float(rec["spread_margin"]),
        )
        source["trace_digest"] = source_digest(source)
        sources_out.append(source)
    record_like = {"sources": sources_out, "version": None}

    # ---- weakness: reconstruct cases, rescore recorded decisions
    cases = incident_cases(record_like)
    n_cases = max(
        (int(d["idx"]) + 1
         for side in ("incumbent", "candidate")
         for d in trace["weakness"][side]["decisions"]),
        default=0,
    )
    cases = cases[: max(n_cases, 0)] if n_cases else []
    margin = float(trace["weakness"]["margin"])
    incumbent = _score_recorded_weakness(
        cases, trace["weakness"]["incumbent"]["decisions"]
    )
    candidate = _score_recorded_weakness(
        cases, trace["weakness"]["candidate"]["decisions"]
    )
    weakness_pass = candidate["score"] > incumbent["score"] + margin

    # ---- gate: rescore recorded placements, re-derive checks
    gspec = ScenarioSpec.from_dict(trace["gate"]["scenario_spec"])
    gscenario = generate_scenario(gspec)
    gate_cfg = trace["gate"]["config"]
    sides = {}
    for side in ("incumbent", "candidate"):
        rec = trace["gate"][side]
        scores = score_placement(
            gscenario, dict(rec["placements"]),
            rec.get("unschedulable", ()),
        )
        sides[side] = {
            "placements": dict(sorted(rec["placements"].items())),
            "unschedulable": sorted(rec.get("unschedulable", ())),
            "scores": scores,
        }
    inc_s, cand_s = sides["incumbent"]["scores"], sides["candidate"]["scores"]
    checks = {
        "spread": cand_s["spread"]
        <= inc_s["spread"] + float(gate_cfg["spread_tolerance"]),
        "constraint_satisfaction": (
            cand_s["constraint_satisfaction"]
            >= inc_s["constraint_satisfaction"]
            - float(gate_cfg["constraint_tolerance"])
        ),
        "bound_frac": (
            cand_s["bound_frac"]
            >= inc_s["bound_frac"] - float(gate_cfg["bound_tolerance"])
        ),
    }
    gate_pass = all(checks.values())

    return {
        "version": LEARN_TRACE_VERSION,
        "seed": trace["seed"],
        "mine": {
            "sources": sources_out,
            "per_class": per_class_counts(sources_out),
            "corpus_digest": corpus_digest(sources_out),
        },
        "weakness": {
            "margin": margin,
            "incumbent": _trace_weakness(incumbent),
            "candidate": _trace_weakness(candidate),
            "pass": weakness_pass,
        },
        "gate": {
            "scenario_spec": gspec.to_dict(),
            "config": dict(gate_cfg),
            "incumbent": sides["incumbent"],
            "candidate": sides["candidate"],
            "checks": checks,
            "pass": gate_pass,
        },
        "action": "promoted" if (weakness_pass and gate_pass) else "rejected",
    }


def verify_learn_trace(path) -> tuple[bool, str]:
    """(ok, detail): replay the recorded learn trace and byte-compare."""
    import difflib

    from k8s_llm_scheduler_tpu.sim.trace import canonical_bytes

    recorded = Path(path).read_bytes()
    replayed = canonical_bytes(replay_learn_trace(json.loads(recorded)))
    recorded_canon = canonical_bytes(json.loads(recorded))
    if replayed == recorded_canon:
        return True, f"bit-identical ({len(replayed)} bytes)"
    a = json.dumps(json.loads(recorded_canon), indent=1, sort_keys=True)
    b = json.dumps(json.loads(replayed), indent=1, sort_keys=True)
    diff = "\n".join(
        list(difflib.unified_diff(
            a.splitlines(), b.splitlines(), "recorded", "replayed"
        ))[:40]
    )
    return False, f"replay diverged:\n{diff}"
