"""Loss-incident mining: turn arena/chaos runs into a training corpus.

The policy-improvement loop (learn/loop.py) starts here. A *loss
incident* is a pod where the serving policy demonstrably lost to the
spread-lookahead reference on the canonical replayable record of a run
(sim/trace.py arena traces, chaos/harness.py chaos reports):

- **unbound**: the arm left the pod unschedulable while the reference
  bound it;
- **constraint**: the arm's placement violates the pod's static
  selector/taint/affinity predicates (core/validation — a K8s-contract
  break, mined unconditionally);
- **divergence**: the arm placed the pod differently from the reference
  in a wave the reference WON — the arm's cumulative fill spread after
  that wave exceeds the reference's by more than `spread_margin`. A
  divergent pod in a wave the arm won (or tied) is taste, not a loss,
  and is deliberately not mined.

Mining is a PURE function of (scenario, candidate placements, reference
placements): `mine_placements` re-derives the per-wave cumulative state
through the deterministic ClusterModel, so the same trace always mines
the same incidents — which is what lets the learn trace replay
byte-identically (learn/loop.py) and what makes an incident corpus a
reproducible artifact rather than a log scrape.

Incidents are deduplicated by (scenario-class, shape, wave, reason):
same-shape pods in one wave are replicas of one pod template (the
decision-cache coherence group — sim/scenarios.py draws constraints once
per shape), so one exemplar with a count carries the same training
signal as thirty copies. Classes come from the shared taxonomy
train/eval.SCENARIO_CLASSES — the corpus's per-class counts speak the
same language as `cli eval --scenarios` and the arena's constraint_mix.

`IncidentCorpus` is the versioned on-disk store: one monotonically
numbered version per mining pass, canonical-JSON sources (the
deterministic mining record: scenario spec, both placement maps,
incidents), a content digest over exactly that deterministic payload,
and provenance — seeds, per-source trace digests, and the registry
checkpoint version that produced the mined placements (the lineage the
registry's retention pinning protects; rollout/registry.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from pathlib import Path
from typing import Any, Callable, Sequence

from k8s_llm_scheduler_tpu.core.validation import (
    node_affinity_matches,
    selector_matches,
    tolerates_taints,
)
from k8s_llm_scheduler_tpu.sim.scenarios import (
    ClusterModel,
    Scenario,
    ScenarioSpec,
    generate_scenario,
)
from k8s_llm_scheduler_tpu.types import NodeMetrics

logger = logging.getLogger(__name__)

MINE_REASONS = ("unbound", "constraint", "divergence")

_VERSION_FMT = "v{:06d}"
_CORPUS_FILE = "corpus.json"
_POINTER = "corpus_index.json"


class CorpusError(RuntimeError):
    pass


def _canonical_bytes(obj: dict) -> bytes:
    # sim/trace.py discipline: one byte-stable serialization everywhere
    from k8s_llm_scheduler_tpu.sim.trace import canonical_bytes

    return canonical_bytes(obj)


def _load_spread(nodes: Sequence[NodeMetrics]) -> float:
    from k8s_llm_scheduler_tpu.train.eval import load_spread

    return load_spread(nodes)


def _static_node_metrics(fact) -> NodeMetrics:
    """A SimNode's static facts as a NodeMetrics for the validation
    predicates (same construction as sim/arena.score_placement)."""
    return NodeMetrics(
        name=fact.name, cpu_usage_percent=0.0, memory_usage_percent=0.0,
        available_cpu_cores=fact.cpu_cores,
        available_memory_gb=fact.memory_gb,
        pod_count=0, max_pods=fact.max_pods,
        labels=dict(fact.labels), taints=fact.taints,
        conditions={"Ready": "True"},
    )


def mine_placements(
    scenario: Scenario,
    placements: dict[str, str],
    unschedulable: Sequence[str],
    ref_placements: dict[str, str],
    ref_unschedulable: Sequence[str],
    *,
    spread_margin: float = 0.005,
) -> list[dict]:
    """Pure incident extraction from two placement maps over one scenario.

    Walks the waves cumulatively through two ClusterModels (candidate and
    reference), judging each wave by the fill spread AFTER it, and each
    pod by the rules in the module docstring. Deterministic: pods iterate
    in name order inside each wave, dedup keys are value tuples, and the
    output is sorted — same inputs, same bytes (the learn-trace replay
    contract rests on this)."""
    node_facts = {n.name: n for n in scenario.nodes}
    cand_model = ClusterModel(scenario)
    ref_model = ClusterModel(scenario)
    # dedup by (kind, shape, wave, reason): same-shape pods in one wave
    # are replicas of one template — one exemplar + count
    buckets: dict[tuple, dict] = {}
    for wave_idx, wave in enumerate(scenario.waves):
        churn = scenario.churn_for_wave(wave_idx)
        cand_model.apply_churn(churn)
        ref_model.apply_churn(churn)
        for pod in wave:
            if pod.name in placements:
                cand_model.place(pod, placements[pod.name])
            if pod.name in ref_placements:
                ref_model.place(pod, ref_placements[pod.name])
        cand_spread = _load_spread(cand_model.metrics())
        ref_spread = _load_spread(ref_model.metrics())
        wave_beaten = cand_spread > ref_spread + spread_margin
        for pod in sorted(wave, key=lambda p: p.name):
            got = placements.get(pod.name)
            ref = ref_placements.get(pod.name)
            reason = None
            if got is None:
                if ref is not None:
                    reason = "unbound"
            else:
                fact = node_facts.get(got)
                spec = pod.to_pod_spec()
                if fact is not None:
                    node = _static_node_metrics(fact)
                    if not (
                        selector_matches(spec, node)
                        and tolerates_taints(spec, node)
                        and node_affinity_matches(spec, node)
                    ):
                        reason = "constraint"
                if reason is None and ref is not None and got != ref \
                        and wave_beaten:
                    reason = "divergence"
            if reason is None:
                continue
            key = (pod.kind, pod.shape, wave_idx, reason)
            bucket = buckets.get(key)
            if bucket is None:
                buckets[key] = {
                    "pod": pod.name,
                    "kind": pod.kind,
                    "shape": pod.shape,
                    "wave": wave_idx,
                    "reason": reason,
                    "count": 1,
                    "got": got,
                    "reference": ref,
                }
            else:
                bucket["count"] += 1
    return sorted(
        buckets.values(),
        key=lambda b: (b["kind"], b["shape"], b["wave"], b["reason"]),
    )


def source_digest(source: dict) -> str:
    """Provenance digest over one source's deterministic payload (the
    recorded placements + incidents — the fields replay recomputes)."""
    payload = {
        k: source[k]
        for k in (
            "scenario_spec", "placements", "unschedulable",
            "ref_placements", "ref_unschedulable", "incidents",
        )
    }
    return hashlib.sha256(_canonical_bytes(payload)).hexdigest()[:16]


def mine_arena_report(
    report: dict,
    arm: str,
    reference: str = "teacher",
    *,
    spread_margin: float = 0.005,
) -> dict:
    """One mined SOURCE record from an arena report (run_arena output with
    its `_traces`, or a loaded sim trace dict with `arms`)."""
    arms = report.get("_traces") or report.get("arms")
    if arms is None or arm not in arms or reference not in arms:
        raise CorpusError(
            f"report has no per-arm placements for {arm!r} vs {reference!r} "
            f"(have {sorted(arms) if arms else []})"
        )
    spec_dict = report.get("scenario") or report.get("scenario_spec")
    if spec_dict is None:
        raise CorpusError("report carries no scenario spec")
    scenario = generate_scenario(ScenarioSpec.from_dict(spec_dict))
    cand = arms[arm]
    ref = arms[reference]
    source = {
        "scenario_spec": ScenarioSpec.from_dict(spec_dict).to_dict(),
        "arm": arm,
        "reference": reference,
        "placements": dict(sorted(cand["placements"].items())),
        "unschedulable": sorted(cand.get("unschedulable", ())),
        "ref_placements": dict(sorted(ref["placements"].items())),
        "ref_unschedulable": sorted(ref.get("unschedulable", ())),
        "spread_margin": spread_margin,
    }
    source["incidents"] = mine_placements(
        scenario,
        source["placements"], source["unschedulable"],
        source["ref_placements"], source["ref_unschedulable"],
        spread_margin=spread_margin,
    )
    source["trace_digest"] = source_digest(source)
    return source


def mine_chaos_report(report: dict, *, spread_margin: float = 0.005) -> dict:
    """A source record from a chaos run report (chaos/harness.run_chaos
    output or a loaded chaos trace): the reference side is the fault-free
    teacher policy replayed over the same scenario — the same comparison
    the report's `quality` section already makes, here per pod."""
    from k8s_llm_scheduler_tpu.sim.arena import _run_policy_arm
    from k8s_llm_scheduler_tpu.sim.teacher import SpreadLookaheadTeacher

    spec = ScenarioSpec.from_dict(report["scenario_spec"])
    scenario = generate_scenario(spec)
    ref_placements, ref_unsched, _waves = _run_policy_arm(
        scenario, SpreadLookaheadTeacher()
    )
    source = {
        "scenario_spec": spec.to_dict(),
        "arm": report.get("regime", "chaos"),
        "reference": "teacher",
        "placements": dict(sorted(report["placements"].items())),
        "unschedulable": sorted(report.get("unschedulable", ())),
        "ref_placements": dict(sorted(ref_placements.items())),
        "ref_unschedulable": sorted(ref_unsched),
        "spread_margin": spread_margin,
    }
    source["incidents"] = mine_placements(
        scenario,
        source["placements"], source["unschedulable"],
        source["ref_placements"], source["ref_unschedulable"],
        spread_margin=spread_margin,
    )
    source["trace_digest"] = source_digest(source)
    return source


def decide_policy_arm(name: str, decide: Callable) -> Any:
    """A bare decide(pod, nodes) function as a sim POLICY arm — the cheap
    mining mode (sequential deterministic replay over the ClusterModel,
    no wire stack). The production surface (`cli learn mine`) runs the
    incumbent as a STACK arm instead; this is for the loop's greedy
    real-engine mining and for tests, where the stack's plumbing is not
    the thing being measured."""
    from k8s_llm_scheduler_tpu.sim.arena import ArmSpec

    class _DecidePolicy:
        def decide(self, pod, nodes):
            return decide(pod, nodes)

    return ArmSpec(name=name, kind="policy", make=_DecidePolicy)


def mine_scenario(
    spec: ScenarioSpec,
    candidate_arm,
    *,
    spread_margin: float = 0.005,
    wave_timeout_s: float = 120.0,
) -> dict:
    """Run one seeded scenario with `candidate_arm` (an sim.ArmSpec)
    against the spread-lookahead teacher and mine the result — the live
    mining path `cli learn mine` and the loop use."""
    from k8s_llm_scheduler_tpu.sim.arena import run_arena, teacher_arm

    scenario = generate_scenario(spec)
    report = run_arena(
        scenario, [candidate_arm, teacher_arm()],
        wave_timeout_s=wave_timeout_s,
    )
    return mine_arena_report(
        report, candidate_arm.name, "teacher", spread_margin=spread_margin
    )


def per_class_counts(sources: Sequence[dict]) -> dict[str, int]:
    from k8s_llm_scheduler_tpu.train.eval import SCENARIO_CLASSES

    counts = {kind: 0 for kind in SCENARIO_CLASSES}
    for source in sources:
        for incident in source["incidents"]:
            counts[incident["kind"]] = (
                counts.get(incident["kind"], 0) + int(incident["count"])
            )
    return {k: v for k, v in counts.items() if v}


def corpus_digest(sources: Sequence[dict]) -> str:
    """Content digest over the DETERMINISTIC corpus payload — the same
    bytes learn-trace replay recomputes, so a trace and the corpus it
    references can never silently disagree."""
    payload = {
        "sources": [
            {
                k: s[k]
                for k in (
                    "scenario_spec", "placements", "unschedulable",
                    "ref_placements", "ref_unschedulable", "incidents",
                )
            }
            for s in sources
        ]
    }
    return hashlib.sha256(_canonical_bytes(payload)).hexdigest()[:16]


class IncidentCorpus:
    """Versioned on-disk incident store: <root>/v000001/corpus.json.

    Same write-aside + rename discipline as the checkpoint registry
    (rollout/registry.py): a version lands atomically or not at all, and
    version ids stay monotonic across deletes via the pointer file."""

    def __init__(self, root: str | Path) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        for stale in self.root.glob(".staging-*"):
            shutil.rmtree(stale, ignore_errors=True)

    # ------------------------------------------------------------- pointer
    def _pointer(self) -> dict:
        p = self.root / _POINTER
        if not p.exists():
            return {"next_version": 1}
        with open(p) as fh:
            return json.load(fh)

    def _write_pointer(self, data: dict) -> None:
        tmp = self.root / (_POINTER + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.root / _POINTER)

    # ------------------------------------------------------------ versions
    def versions(self) -> list[int]:
        out = []
        for d in self.root.iterdir():
            if d.is_dir() and d.name.startswith("v") and (
                d / _CORPUS_FILE
            ).exists():
                try:
                    out.append(int(d.name[1:]))
                except ValueError:
                    continue
        return sorted(out)

    def get(self, version: int) -> dict:
        path = self.root / _VERSION_FMT.format(version) / _CORPUS_FILE
        if not path.exists():
            raise CorpusError(
                f"corpus {self.root}: no version {version} "
                f"(have {self.versions()})"
            )
        with open(path) as fh:
            return json.load(fh)

    def latest(self) -> dict | None:
        versions = self.versions()
        return self.get(versions[-1]) if versions else None

    # --------------------------------------------------------------- write
    def add_version(
        self,
        sources: Sequence[dict],
        *,
        checkpoint_version: int | None = None,
        note: str = "",
    ) -> dict:
        """Persist one mining pass as the next corpus version.

        `checkpoint_version` is the registry version whose decisions were
        mined — the corpus lineage pointer retention pinning protects
        (rollout/registry.retain pinned set)."""
        sources = [dict(s) for s in sources]
        if not sources:
            raise CorpusError("refusing to write an empty corpus version")
        n_incidents = sum(
            int(i["count"]) for s in sources for i in s["incidents"]
        )
        if not n_incidents:
            raise CorpusError(
                "mining produced zero incidents — nothing to learn from "
                "(the candidate beat the reference everywhere)"
            )
        ptr = self._pointer()
        version = int(ptr["next_version"])
        record = {
            "version": version,
            "created_at": time.time(),  # graftlint: ok[raw-clock, wall-clock-in-replay] — wall-clock metadata for operators, never compared against durations
            "checkpoint_version": checkpoint_version,
            "note": note,
            "per_class": per_class_counts(sources),
            "n_incidents": n_incidents,
            "digest": corpus_digest(sources),
            "sources": sources,
        }
        staging = self.root / f".staging-{_VERSION_FMT.format(version)}-{os.getpid()}"
        if staging.exists():
            shutil.rmtree(staging)
        staging.mkdir(parents=True)
        try:
            with open(staging / _CORPUS_FILE, "w", encoding="utf-8") as fh:
                json.dump(record, fh, indent=1, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            os.rename(staging, self.root / _VERSION_FMT.format(version))
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        ptr["next_version"] = version + 1
        self._write_pointer(ptr)
        logger.info(
            "incident corpus v%d: %d incidents across %d source(s) %s",
            version, n_incidents, len(sources), record["per_class"],
        )
        return record

    # --------------------------------------------------------------- reads
    def lineage_versions(self) -> set[int]:
        """Registry checkpoint versions referenced by ANY corpus version —
        the set the registry's retention walk must never evict."""
        out: set[int] = set()
        for v in self.versions():
            ckpt = self.get(v).get("checkpoint_version")
            if ckpt is not None:
                out.add(int(ckpt))
        return out

    def status(self) -> dict:
        versions = []
        for v in self.versions():
            record = self.get(v)
            versions.append({
                "version": v,
                "n_incidents": record["n_incidents"],
                "per_class": record["per_class"],
                "checkpoint_version": record.get("checkpoint_version"),
                "digest": record["digest"],
                "note": record.get("note", ""),
                "sources": len(record["sources"]),
            })
        return {"root": str(self.root), "versions": versions}
