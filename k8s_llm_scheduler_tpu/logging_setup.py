"""Structured logging setup.

Behavioral parity with the reference's module-level logging config (reference
scheduler.py:26-41): level and format chosen from config/env. The reference's
"json" format is just the bare message (scheduler.py:31-34); here json format
emits real JSON lines with timestamp/level/logger/message.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload: dict[str, Any] = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def setup_logging(level: str = "INFO", fmt: str = "text", file: str | None = None) -> None:
    handlers: list[logging.Handler] = []
    stream = logging.StreamHandler(sys.stderr)
    handlers.append(stream)
    if file:
        handlers.append(logging.FileHandler(file))

    formatter: logging.Formatter
    if fmt == "json":
        formatter = JsonFormatter()
    else:
        formatter = logging.Formatter(
            "%(asctime)s - %(name)s - %(levelname)s - %(message)s"
        )
    root = logging.getLogger("k8s_llm_scheduler_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.handlers.clear()
    for handler in handlers:
        handler.setFormatter(formatter)
        root.addHandler(handler)
