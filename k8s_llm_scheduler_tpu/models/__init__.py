"""Decision-model families in functional JAX (Llama 3.x dense)."""

from k8s_llm_scheduler_tpu.models.configs import (  # noqa: F401
    LLAMA_3_1_8B,
    LLAMA_3_2_1B,
    LLAMA_3_3_70B,
    TINY,
    LlamaConfig,
    get_config,
)
