"""Llama model-family configurations.

The reference consumes Llama-3.3-70B-Instruct behind the HuggingFace API
(reference scheduler.py:425, config.yaml:8); the BASELINE ladder also names
Llama-3.2-1B and Llama-3.1-8B (BASELINE.json configs). These are the public
architecture hyperparameters for those checkpoints, plus a TINY config for
tests/benches that exercises every code path (GQA, RoPE scaling, stacked
scan) at toy scale.

All sizes are chosen/padded with the TPU in mind: vocab and hidden dims are
multiples of 128 (MXU lane width), head_dim 64/128 (VPU/MXU friendly).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.x rope frequency scaling (the "llama3" scheme)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: RopeScaling | None = None
    rms_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    def __post_init__(self) -> None:
        assert self.d_model % self.n_heads == 0
        assert self.n_heads % self.n_kv_heads == 0


TINY = LlamaConfig(
    name="tiny",
    vocab_size=512,          # byte tokenizer fits in 512
    d_model=256,
    n_layers=4,
    n_heads=4,
    n_kv_heads=2,            # exercises GQA
    d_ff=512,
    max_seq_len=2048,
    rope_theta=10000.0,
    rope_scaling=None,
    tie_embeddings=True,
)

# A mid-size test config: big enough that kernels/meshes matter, small enough
# to run on one chip in seconds.
SMALL = LlamaConfig(
    name="small",
    vocab_size=512,
    d_model=1024,
    n_layers=8,
    n_heads=8,
    n_kv_heads=4,
    d_ff=2816,
    max_seq_len=8192,
    rope_theta=500000.0,
    tie_embeddings=True,
)

LLAMA_3_2_1B = LlamaConfig(
    name="llama-3.2-1b-instruct",
    vocab_size=128256,
    d_model=2048,
    n_layers=16,
    n_heads=32,
    n_kv_heads=8,
    d_ff=8192,
    max_seq_len=131072,
    rope_theta=500000.0,
    rope_scaling=RopeScaling(factor=32.0),
    tie_embeddings=True,
)

LLAMA_3_1_8B = LlamaConfig(
    name="llama-3.1-8b-instruct",
    vocab_size=128256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14336,
    max_seq_len=131072,
    rope_theta=500000.0,
    rope_scaling=RopeScaling(factor=8.0),
)

LLAMA_3_3_70B = LlamaConfig(
    name="llama-3.3-70b-instruct",
    vocab_size=128256,
    d_model=8192,
    n_layers=80,
    n_heads=64,
    n_kv_heads=8,
    d_ff=28672,
    max_seq_len=131072,
    rope_theta=500000.0,
    rope_scaling=RopeScaling(factor=8.0),
)

_REGISTRY = {
    c.name: c for c in (TINY, SMALL, LLAMA_3_2_1B, LLAMA_3_1_8B, LLAMA_3_3_70B)
}


def get_config(name: str) -> LlamaConfig:
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown model config {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
