"""Llama 3.x in pure functional JAX: RMSNorm, RoPE, GQA, SwiGLU.

This is the in-tree decision model that replaces the reference's network call
to HF-hosted Llama (reference scheduler.py:425-433). Design choices are
TPU/XLA-first, not a torch translation:

- **Pure pytrees, no Module system**: params are nested dicts of arrays;
  every entry point is a pure function of (params, inputs) and jit/pjit
  composes directly. Sharding is applied to the pytree from
  parallel/sharding.py PartitionSpecs.
- **Stacked layers + lax.scan**: all transformer blocks live in ONE stacked
  pytree (leading axis = layer), so XLA compiles one block body regardless of
  depth — 80-layer 70B compiles as fast as the 4-layer test config and the
  weights pytree is scan/pjit friendly.
- **Static shapes everywhere**: padded prompt buckets, fixed decode batch,
  masking instead of dynamic shapes, so nothing falls off the jit path.
- **Paged KV cache at decode**: the decode step scatters the new token's K/V
  into cache pages and attends via ops/attention.paged_decode_attention.
- bf16 weights/activations, f32 norm/softmax/logits accumulation (MXU-native).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.ops.attention import (
    attend_part,
    causal_prefill_attention,
    chunk_attention_with_prefix,
    merge_attention_parts,
    paged_decode_attention,
    prefix_attend_parts,
)

Params = dict[str, Any]


# --------------------------------------------------------------------- norm
def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    """RMSNorm in f32, result back in input dtype."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


# --------------------------------------------------------------------- rope
def rope_inv_freq(cfg: LlamaConfig) -> jax.Array:
    """Inverse RoPE frequencies with optional llama3 long-context scaling."""
    head_dim = cfg.head_dim
    inv = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    s = cfg.rope_scaling
    if s is None:
        return inv
    # llama3 scheme: low-freq bands divided by factor, high-freq kept,
    # smooth interpolation in between.
    wavelen = 2.0 * jnp.pi / inv
    low_wl = s.original_max_position / s.low_freq_factor
    high_wl = s.original_max_position / s.high_freq_factor
    smooth = (s.original_max_position / wavelen - s.low_freq_factor) / (
        s.high_freq_factor - s.low_freq_factor
    )
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = jnp.where(
        wavelen > low_wl,
        inv / s.factor,
        jnp.where(wavelen < high_wl, inv, (1 - smooth) * inv / s.factor + smooth * inv),
    )
    return scaled


def apply_rope(
    x: jax.Array,  # [..., n_heads, head_dim]
    positions: jax.Array,  # broadcastable to x's leading dims
    inv_freq: jax.Array,  # [head_dim//2]
) -> jax.Array:
    """Rotary embedding at absolute positions (half-split layout)."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., hd//2]
    cos = jnp.cos(angles)[..., None, :]  # [..., 1, hd//2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


# --------------------------------------------------------------------- init
def init_params(
    rng: jax.Array, cfg: LlamaConfig, quantize: str | None = None
) -> Params:
    """Random-init params with stacked layers (leading axis = n_layers).

    quantize="int8" converts each dense weight AS IT IS CREATED
    (models/quant.py, donated) — peak device memory is the int8 model plus
    one bf16 weight, which is what lets an 8B config random-init on a
    single 16 GB chip.
    """
    hd = cfg.head_dim
    keys = jax.random.split(rng, 10)
    if quantize is not None:
        from k8s_llm_scheduler_tpu.models.quant import _quantize_weight_donated

        if quantize != "int8":
            raise ValueError(f"unknown quantization {quantize!r}")

    def norm_init(shape):
        return jnp.ones(shape, dtype=cfg.dtype)

    def dense_init(key, shape, in_dim):
        scale = in_dim**-0.5
        w = (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(
            cfg.dtype
        )
        if quantize is not None and len(shape) == 3:  # stacked layer weights
            return _quantize_weight_donated(w)
        return w

    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    params: Params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, D), dtype=jnp.float32) * 0.02
        ).astype(cfg.dtype),
        "final_norm": norm_init((D,)),
        "layers": {
            "attn_norm": norm_init((L, D)),
            "wq": dense_init(keys[1], (L, D, cfg.n_heads * hd), D),
            "wk": dense_init(keys[2], (L, D, cfg.n_kv_heads * hd), D),
            "wv": dense_init(keys[3], (L, D, cfg.n_kv_heads * hd), D),
            "wo": dense_init(keys[4], (L, cfg.n_heads * hd, D), cfg.n_heads * hd),
            "mlp_norm": norm_init((L, D)),
            "w_gate": dense_init(keys[5], (L, D, F), D),
            "w_up": dense_init(keys[6], (L, D, F), D),
            "w_down": dense_init(keys[7], (L, F, D), F),
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[8], (D, cfg.vocab_size), D)
    return params


def _layer_slice(layers: Params, i: int | jax.Array) -> Params:
    return jax.tree_util.tree_map(lambda a: a[i], layers)


def _logits(params: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    """LM head with f32 ACCUMULATION but native-dtype operands: casting a
    128k-vocab embedding to f32 materializes a multi-GB transient per model
    call (it OOMed the 8B single-chip config); preferred_element_type gets
    the f32 accumulate without the f32 copy."""
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if cfg.tie_embeddings:
        return jnp.einsum(
            "...d,vd->...v", x, params["embed"],
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "...d,dv->...v", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )


def _dense(x: jax.Array, w, eq: str) -> jax.Array:
    """Dense projection dispatching on weight form: plain array, or the
    int8 weight-only pair {"q", "scale"} (models/quant.py) — the dequant
    convert fuses into the matmul, the per-channel scale broadcasts over
    the output axis."""
    if isinstance(w, dict):
        out = jnp.einsum(eq, x, w["q"].astype(x.dtype))
        return (out.astype(jnp.float32) * w["scale"]).astype(x.dtype)
    return jnp.einsum(eq, x, w)


def _mlp(lp: Params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = _dense(h, lp["w_gate"], "...d,df->...f")
    up = _dense(h, lp["w_up"], "...d,df->...f")
    fused = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    return _dense(fused, lp["w_down"], "...f,fd->...d")


# ------------------------------------------------------------------ prefill
def prefill_layer(
    lp: Params,
    cfg: LlamaConfig,
    x: jax.Array,  # [B, S, D]
    positions: jax.Array,  # [B, S]
    seq_lens: jax.Array,  # [B]
    inv_freq: jax.Array,
    attn_fn: Any = None,
) -> jax.Array:
    """One transformer layer of full-prompt prefill (shared by
    forward_prefill and the pipeline-parallel trunk, train/pipeline.py)."""
    B, S = x.shape[:2]
    hd = cfg.head_dim
    attn_impl = attn_fn if attn_fn is not None else causal_prefill_attention
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _dense(h, lp["wq"], "bsd,dh->bsh").reshape(B, S, cfg.n_heads, hd)
    k = _dense(h, lp["wk"], "bsd,dh->bsh").reshape(B, S, cfg.n_kv_heads, hd)
    v = _dense(h, lp["wv"], "bsd,dh->bsh").reshape(B, S, cfg.n_kv_heads, hd)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    attn = attn_impl(q, k, v, seq_lens)
    attn = _dense(attn.reshape(B, S, cfg.n_heads * hd), lp["wo"], "bsh,hd->bsd")
    x = x + attn
    x = x + _mlp(lp, cfg, x)
    return x, (k, v)


def forward_prefill(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] int32, left-aligned, padded
    seq_lens: jax.Array,  # [B]
    attn_impl: Any = None,  # (q,k,v,seq_lens)->out; default causal full attn
    return_logits: bool = True,  # static; False skips the LM head (KV-only)
    remat: bool = False,  # static; checkpoint each layer (training path)
    return_hidden: bool = False,  # static; also return the final-layer
    # pre-norm residual stream [B, S, D] (hidden-transfer head training)
) -> tuple[jax.Array | None, jax.Array, jax.Array] | tuple[
    jax.Array | None, jax.Array, jax.Array, jax.Array
]:
    """Full-prompt forward pass.

    Returns (logits [B,S,V] f32, k_all [L,B,S,n_kv,hd], v_all [...]) — the
    engine scatters k_all/v_all into KV cache pages (engine/kv_cache.py).
    With return_logits=False, logits is None — the prefix-prefill path only
    needs KV, and a full-bucket [S, vocab] logits tensor is pure waste
    (~8 GB at 128k vocab x 16k bucket).

    `attn_impl` swaps the attention kernel: the training path passes a
    ring-attention wrapper (parallel/ring_attention.py) when the mesh has a
    sequence-parallel axis. Must be static under jit (pass via closure or
    static_argnums).

    `remat=True` wraps each scanned layer in jax.checkpoint so the
    backward pass rematerializes per-layer activations instead of keeping
    all L layers' intermediates live — the standard HBM-for-FLOPs trade
    (~25-30% more compute for ~1/L the activation memory). Inference
    callers never set it; the train step does (measured: the small config
    at batch 6 x seq 2048 compiles to 16.7 GB without remat — over a
    v5e's 15.75 GB — and well under with it).
    """
    B, S = tokens.shape
    inv_freq = rope_inv_freq(cfg)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    x = params["embed"][tokens]  # [B, S, D]

    def body(x, lp):
        return prefill_layer(lp, cfg, x, positions, seq_lens, inv_freq, attn_impl)

    if remat:
        body = jax.checkpoint(body)
    x, (k_all, v_all) = jax.lax.scan(body, x, params["layers"])
    logits = _logits(params, cfg, x) if return_logits else None
    if return_hidden:
        return logits, k_all, v_all, x
    return logits, k_all, v_all


# ------------------------------------------------- suffix prefill (cascade)
def _suffix_layer(
    lp: Params,
    cfg: LlamaConfig,
    x: jax.Array,  # [B, S, D]
    positions: jax.Array,  # [B, S]
    suffix_lens: jax.Array,  # [B]
    pk: jax.Array,  # [Sp, n_kv, hd] this layer's shared prefix KV
    pv: jax.Array,
    prefix_len: jax.Array,
    inv_freq: jax.Array,
    prefix_impl: str | None = None,  # static — ops/attention.prefix_attend_parts
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One transformer layer of cascade suffix prefill: attends to the
    shared dense prefix + causally within the suffix. Shared by the paged
    (forward_prefill_suffix) and dense/wave (forward_prefill_suffix_dense)
    paths, which differ only in where the suffix K/V is sunk.
    Returns (x_out, k, v)."""
    B, S = x.shape[:2]
    hd = cfg.head_dim
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = _dense(h, lp["wq"], "bsd,dh->bsh").reshape(B, S, cfg.n_heads, hd)
    k = _dense(h, lp["wk"], "bsd,dh->bsh").reshape(B, S, cfg.n_kv_heads, hd)
    v = _dense(h, lp["wv"], "bsd,dh->bsh").reshape(B, S, cfg.n_kv_heads, hd)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    attn = chunk_attention_with_prefix(
        q, k, v, suffix_lens, pk, pv, prefix_len, prefix_impl=prefix_impl
    )
    attn = _dense(attn.reshape(B, S, cfg.n_heads * hd), lp["wo"], "bsh,hd->bsd")
    x = x + attn
    x = x + _mlp(lp, cfg, x)
    return x, k, v


def _last_valid_logits(
    params: Params, cfg: LlamaConfig, x: jax.Array, lens: jax.Array
) -> jax.Array:
    """Logits at each row's final valid token ([B, S, D], [B] -> [B, V])."""
    last_idx = jnp.maximum(lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    return _logits(params, cfg, x_last)


def forward_prefill_suffix(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, Ss] int32 — per-request suffix, left-aligned
    suffix_lens: jax.Array,  # [B] valid suffix tokens (0 = row unused)
    prefix_k_all: jax.Array,  # [L, Sp, n_kv, hd] — shared dense prefix KV
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,  # scalar int32 — valid prefix tokens (0 = none)
    k_cache: jax.Array,  # [L, num_pages, page_size, n_kv, hd] (donate)
    v_cache: jax.Array,
    page_ids: jax.Array,  # [B, Ss/page_size] dest page per suffix block (0=scratch)
    prefix_impl: str | None = None,  # static
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched suffix prefill against a shared dense prefix.

    The whole burst's per-pod prompt tails prefill in ONE program: each row
    attends to the burst-shared cluster-state prefix (read once from HBM via
    cascade attention, ops/attention.py) plus causally within its own
    suffix; the suffix K/V is scattered straight into the paged KV cache.
    Returns (last_logits [B,V] f32 — logits at each row's final valid token,
    k_cache, v_cache). This replaces per-request full-prompt prefill for the
    scheduling-burst workload (the reference pays a full remote prefill per
    pod, reference scheduler.py:425-433).
    """
    B, S = tokens.shape
    hd = cfg.head_dim
    page_size = k_cache.shape[2]
    n_blocks = S // page_size
    inv_freq = rope_inv_freq(cfg)
    positions = prefix_len + jnp.broadcast_to(jnp.arange(S), (B, S))

    x = params["embed"][tokens]  # [B, S, D]
    layer_ids = jnp.arange(cfg.n_layers)

    def body(carry, xs):
        x, kc, vc = carry
        lp, pk, pv, idx = xs
        x, k, v = _suffix_layer(
            lp, cfg, x, positions, suffix_lens, pk, pv, prefix_len, inv_freq,
            prefix_impl=prefix_impl,
        )
        # Scatter this layer's suffix K/V blocks into their pages (padding
        # blocks were routed to the reserved scratch page 0 by the caller).
        blocks_k = k.reshape(B, n_blocks, page_size, cfg.n_kv_heads, hd)
        blocks_v = v.reshape(B, n_blocks, page_size, cfg.n_kv_heads, hd)
        kc = kc.at[idx, page_ids].set(blocks_k.astype(kc.dtype))
        vc = vc.at[idx, page_ids].set(blocks_v.astype(vc.dtype))
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        body, (x, k_cache, v_cache),
        (params["layers"], prefix_k_all, prefix_v_all, layer_ids),
    )
    return _last_valid_logits(params, cfg, x, suffix_lens), k_cache, v_cache


def forward_prefill_suffix_dense(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, Ss] int32 — per-request suffix, left-aligned
    suffix_lens: jax.Array,  # [B] valid suffix tokens (0 = row unused)
    prefix_k_all: jax.Array,  # [L, Sp, n_kv, hd] — shared dense prefix KV
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,  # scalar int32 — valid prefix tokens (0 = none)
    prefix_impl: str | None = None,  # static
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched suffix prefill against a shared dense prefix, KV kept DENSE.

    Identical attention semantics to forward_prefill_suffix, but instead of
    scattering suffix K/V into paged-cache pages it returns the stacked
    dense buffers (k_sfx, v_sfx) [L, B, Ss, n_kv, hd]. This is the first
    stage of the fused decision wave (engine/engine.py _wave_impl): the wave
    decodes to completion against (prefix | dense suffix | chunk buffer)
    without ever touching the paged KV cache — no page allocation, no
    gather/flush traffic, no multi-hundred-MB donation per dispatch.
    Returns (last_logits [B, V] f32, k_sfx, v_sfx).
    """
    B, S = tokens.shape
    inv_freq = rope_inv_freq(cfg)
    positions = prefix_len + jnp.broadcast_to(jnp.arange(S), (B, S))

    x = params["embed"][tokens]  # [B, S, D]

    def body(x, xs):
        lp, pk, pv = xs
        x, k, v = _suffix_layer(
            lp, cfg, x, positions, suffix_lens, pk, pv, prefix_len, inv_freq,
            prefix_impl=prefix_impl,
        )
        return x, (k, v)

    x, (k_sfx, v_sfx) = jax.lax.scan(
        body, x, (params["layers"], prefix_k_all, prefix_v_all)
    )
    return _last_valid_logits(params, cfg, x, suffix_lens), k_sfx, v_sfx


def forward_prefill_packed(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,     # [C] int32 — one packed chunk (pad holes)
    seg: jax.Array,        # [C] int32 — segment id per token, -1 on padding
    positions: jax.Array,  # [C] int32 — ABSOLUTE position of each token
    prefix_k_all: jax.Array,  # [L, Sp, n_kv, hd] — shared dense prefix KV
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,    # scalar int32
    carry_k: jax.Array,    # [L, CAP, n_kv, hd] pack carry (donate)
    carry_v: jax.Array,
    carry_seg: jax.Array,  # [CAP] int32 segment per carry entry (-1 empty)
    carry_len: jax.Array,  # scalar int32 — tokens already in the carry
    k_cache: jax.Array,    # [L, num_pages, page_size, n_kv, hd] (donate)
    v_cache: jax.Array,
    page_ids: jax.Array,   # [C] per-token dest page (0 = scratch)
    offs: jax.Array,       # [C] per-token dest offset within the page
    end_idx: jax.Array,    # [E] chunk-local indices of prompt-final tokens
    prefix_impl: str | None = None,  # static
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """One PACKED prefill chunk: many prompts in one token stream with
    BLOCK-DIAGONAL attention (the Prepacking scheme, arXiv:2404.09529).

    Token i (segment s_i) attends to:
    - the burst-shared dense prefix (every real token — the prompts all
      continue the same cluster-state prefix);
    - carry entries of the SAME segment (this prompt's tokens from earlier
      chunks of the pack — how a prompt spans a chunk boundary);
    - chunk tokens j <= i of the SAME segment (causal within the prompt,
      blocked across prompts).

    Padding tokens (seg -1) only ever match other padding (their K/V
    lands in the scratch page / is never attended by real queries), so a
    partially-filled final chunk needs no special casing. The chunk's K/V
    is scattered per token into the paged KV cache (each prompt's slot
    pages) AND appended to the pack carry at `carry_len`.

    Returns (end_logits [E, V] f32 — logits at each listed prompt-final
    token, carry_k, carry_v, carry_seg, k_cache, v_cache). Semantically
    this computes EXACTLY what per-prompt serial prefill computes — the
    token-identity test pins packed+chunked greedy decode against the
    serial whole-prompt path (tests/test_admission.py).
    """
    C = tokens.shape[0]
    CAP = carry_k.shape[1]
    hd = cfg.head_dim
    inv_freq = rope_inv_freq(cfg)

    x = params["embed"][tokens][None]  # [1, C, D]
    pos_b = positions[None, :]  # [1, C]

    # Masks are layer-independent: build once outside the scan.
    carry_mask = (
        (jnp.arange(CAP)[None, :] < carry_len)
        & (carry_seg[None, :] == seg[:, None])
    )[None, None, None, :, :]  # [1, 1, 1, C, CAP]
    j = jnp.arange(C)
    blk_mask = (
        (j[:, None] >= j[None, :]) & (seg[:, None] == seg[None, :])
    )[None, None, None, :, :]  # [1, 1, 1, C, C]

    def body(carry, xs):
        x, ck, cv, kc, vc = carry
        lp, pk, pv, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bsd,dh->bsh").reshape(1, C, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bsd,dh->bsh").reshape(1, C, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bsd,dh->bsh").reshape(1, C, cfg.n_kv_heads, hd)
        q = apply_rope(q, pos_b, inv_freq)
        k = apply_rope(k, pos_b, inv_freq)
        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
            1, C, cfg.n_kv_heads, cfg.q_per_kv, hd
        )
        parts = [
            prefix_attend_parts(q, qg, pk, pv, prefix_len, impl=prefix_impl),
            attend_part(
                qg, ck[idx][None], cv[idx][None], carry_mask,
                "bqkgh,bskh->bkgqs",
            ),
            attend_part(qg, k, v, blk_mask, "bqkgh,bskh->bkgqs"),
        ]
        attn = merge_attention_parts(parts)  # [1, n_kv, g, C, hd]
        attn = jnp.moveaxis(attn, 3, 1).reshape(1, C, cfg.n_heads * hd)
        attn = _dense(attn.astype(x.dtype), lp["wo"], "bsh,hd->bsd")
        x = x + attn
        x = x + _mlp(lp, cfg, x)
        # Scatter this chunk's K/V into the paged cache (per-token dests;
        # padding routed to the reserved scratch page 0 by the caller)...
        kc = kc.at[idx, page_ids, offs].set(k[0].astype(kc.dtype))
        vc = vc.at[idx, page_ids, offs].set(v[0].astype(vc.dtype))
        # ...and append it to the pack carry so later chunks of a
        # boundary-spanning prompt can attend their earlier tokens.
        layer_k = jax.lax.dynamic_update_slice_in_dim(
            ck[idx], k[0].astype(ck.dtype), carry_len, axis=0
        )
        layer_v = jax.lax.dynamic_update_slice_in_dim(
            cv[idx], v[0].astype(cv.dtype), carry_len, axis=0
        )
        ck = jax.lax.dynamic_update_index_in_dim(ck, layer_k, idx, axis=0)
        cv = jax.lax.dynamic_update_index_in_dim(cv, layer_v, idx, axis=0)
        return (x, ck, cv, kc, vc), None

    (x, carry_k, carry_v, k_cache, v_cache), _ = jax.lax.scan(
        body,
        (x, carry_k, carry_v, k_cache, v_cache),
        (
            params["layers"], prefix_k_all, prefix_v_all,
            jnp.arange(cfg.n_layers),
        ),
    )
    carry_seg = jax.lax.dynamic_update_slice(carry_seg, seg, (carry_len,))
    # LM head only at the prompt-final tokens: the full [C, V] logits
    # tensor is pure waste on the admission path.
    x_end = x[0][end_idx]  # [E, D]
    return _logits(params, cfg, x_end), carry_k, carry_v, carry_seg, k_cache, v_cache


def forward_block_decode(
    params: Params,
    cfg: LlamaConfig,
    blk_tok: jax.Array,  # [R, F] int32 — this iteration's token block
    blk_valid: jax.Array,  # [R, F] bool — left-aligned valid tokens
    blk_len: jax.Array,  # [R] int32 — number of valid tokens (= blk_valid sum)
    positions: jax.Array,  # [R, F] absolute positions
    k_sfx: jax.Array,  # [L, R, Ss, n_kv, hd] dense suffix KV
    v_sfx: jax.Array,
    suffix_lens: jax.Array,  # [R]
    gen_k: jax.Array,  # [L, R, cap+1, n_kv, hd] generated-token KV (donated)
    gen_v: jax.Array,
    tail: jax.Array,  # [R] tokens already in gen_k/gen_v
    prefix_k_all: jax.Array,  # [L, Sp, n_kv, hd] shared dense prefix
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,  # scalar int32
    prefix_impl: str | None = None,  # static
    ragged: bool = False,  # static: ragged-M Pallas matmuls (single device)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One grammar-accelerated decode iteration: an F-wide mini-prefill.

    Where per-token decode runs the model once per emitted token, block
    decode runs it once per ITERATION, consuming a whole (sampled + forced)
    token run: every valid block token attends to the shared dense prefix,
    its row's dense suffix, the generated-so-far buffer, and causally within
    the block, all in one pass — so a forced JSON-skeleton span costs one
    model call instead of one per character. Invalid block slots write their
    K/V to the buffer's trash slot (index cap).

    `ragged=True` removes the F-width padding from every projection/MLP
    matmul (SCALING.md wave roofline: 62% of decode compute at the
    250-token point): valid tokens are compacted to the front of the
    flattened [R*F] axis once per iteration (argsort shared by all
    layers), the residual stream stays compacted through the scan, and
    matmuls run in ops/ragged_matmul with the valid-token count scalar-
    prefetched so FLOPs scale with real tokens. Attention and K/V
    bookkeeping stay in the [R, F] layout (they are the small term and
    are row-structured); q/k/v scatter back through the inverse
    permutation. Dead compacted rows carry garbage — every consumer
    masks by blk_valid / trash-slot dest, exactly as the dense path
    already requires.

    Returns (logits [R, V] f32 at each row's LAST VALID block position,
    gen_k, gen_v).
    """
    R, F = blk_tok.shape
    hd = cfg.head_dim
    cap1 = gen_k.shape[2]  # cap + 1 (trash slot at index cap)
    inv_freq = rope_inv_freq(cfg)

    x = params["embed"][blk_tok]  # [R, F, D]
    Ss = k_sfx.shape[2]

    if ragged:
        from k8s_llm_scheduler_tpu.ops.ragged_matmul import ragged_matmul

        flat_valid = blk_valid.reshape(R * F)
        perm = jnp.argsort(jnp.logical_not(flat_valid), stable=True)
        inv_perm = jnp.argsort(perm)
        total = jnp.sum(blk_len)
        # last valid token of row r in compacted order (rows with len 0
        # clamp to 0 — their logits are never consumed, same contract as
        # the dense path's max(len-1, 0))
        last_c = jnp.maximum(jnp.cumsum(blk_len) - 1, 0)

        def _rdense(h, w):
            return ragged_matmul(h, w, total)

    sfx_mask = (jnp.arange(Ss)[None, :] < suffix_lens[:, None])[
        :, None, None, None, :
    ]
    gen_mask = (jnp.arange(cap1)[None, :] < tail[:, None])[:, None, None, None, :]
    j = jnp.arange(F)
    blk_mask = (
        (j[:, None] >= j[None, :])[None, :, :] & blk_valid[:, None, :]
    )[:, None, None, :, :]  # [R, 1, 1, F_q, F_kv]

    # K/V scatter destinations: valid token j -> tail + j, invalid -> trash.
    dest = jnp.where(blk_valid, tail[:, None] + j[None, :], cap1 - 1)  # [R, F]
    row = jnp.arange(R)[:, None]

    if ragged:
        xc = x.reshape(R * F, -1)[perm]  # valid tokens first

        def body_ragged(carry, xs):
            xc, gk, gv = carry
            lp, pk, pv, ks, vs, idx = xs
            h = rms_norm(xc, lp["attn_norm"], cfg.rms_eps)
            q = _rdense(h, lp["wq"])[inv_perm].reshape(
                R, F, cfg.n_heads, hd
            )
            k = _rdense(h, lp["wk"])[inv_perm].reshape(
                R, F, cfg.n_kv_heads, hd
            )
            v = _rdense(h, lp["wv"])[inv_perm].reshape(
                R, F, cfg.n_kv_heads, hd
            )
            q = apply_rope(q, positions, inv_freq)
            k = apply_rope(k, positions, inv_freq)
            qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
                R, F, cfg.n_kv_heads, cfg.q_per_kv, hd
            )
            parts = [
                prefix_attend_parts(q, qg, pk, pv, prefix_len, impl=prefix_impl),
                attend_part(qg, ks, vs, sfx_mask, "bqkgh,bskh->bkgqs"),
                attend_part(qg, gk[idx], gv[idx], gen_mask, "bqkgh,bskh->bkgqs"),
                attend_part(qg, k, v, blk_mask, "bqkgh,bskh->bkgqs"),
            ]
            attn = merge_attention_parts(parts)
            attn = jnp.moveaxis(attn, 3, 1).reshape(R * F, cfg.n_heads * hd)
            attn_c = attn[perm].astype(xc.dtype)
            xc = xc + _rdense(attn_c, lp["wo"])
            h2 = rms_norm(xc, lp["mlp_norm"], cfg.rms_eps)
            gate = _rdense(h2, lp["w_gate"])
            up = _rdense(h2, lp["w_up"])
            fused = jax.nn.silu(gate.astype(jnp.float32)).astype(xc.dtype) * up
            xc = xc + _rdense(fused, lp["w_down"])
            gk = gk.at[idx, row, dest].set(k.astype(gk.dtype))
            gv = gv.at[idx, row, dest].set(v.astype(gv.dtype))
            return (xc, gk, gv), None

        (xc, gen_k, gen_v), _ = jax.lax.scan(
            body_ragged,
            (xc, gen_k, gen_v),
            (
                params["layers"], prefix_k_all, prefix_v_all,
                k_sfx, v_sfx, jnp.arange(cfg.n_layers),
            ),
        )
        return _logits(params, cfg, xc[last_c]), gen_k, gen_v

    def body(carry, xs):
        x, gk, gv = carry
        lp, pk, pv, ks, vs, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bfd,dh->bfh").reshape(R, F, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bfd,dh->bfh").reshape(R, F, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bfd,dh->bfh").reshape(R, F, cfg.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)

        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
            R, F, cfg.n_kv_heads, cfg.q_per_kv, hd
        )
        # Read this layer's generated-token KV from the carry: gen_mask only
        # exposes entries < tail (previous iterations), so the read never
        # sees this iteration's (not yet written) block.
        parts = [
            prefix_attend_parts(q, qg, pk, pv, prefix_len, impl=prefix_impl),
            attend_part(qg, ks, vs, sfx_mask, "bqkgh,bskh->bkgqs"),
            attend_part(qg, gk[idx], gv[idx], gen_mask, "bqkgh,bskh->bkgqs"),
            attend_part(qg, k, v, blk_mask, "bqkgh,bskh->bkgqs"),
        ]
        attn = merge_attention_parts(parts)  # [R, n_kv, g, F, hd]
        attn = jnp.moveaxis(attn, 3, 1).reshape(R, F, cfg.n_heads * hd)
        attn = _dense(attn.astype(x.dtype), lp["wo"], "bfh,hd->bfd")
        x = x + attn
        x = x + _mlp(lp, cfg, x)
        # write the block's K/V AFTER attention (in-block attention came
        # from the dense k/v just computed)
        gk = gk.at[idx, row, dest].set(k.astype(gk.dtype))
        gv = gv.at[idx, row, dest].set(v.astype(gv.dtype))
        return (x, gk, gv), None

    (x, gen_k, gen_v), _ = jax.lax.scan(
        body,
        (x, gen_k, gen_v),
        (
            params["layers"], prefix_k_all, prefix_v_all,
            k_sfx, v_sfx, jnp.arange(cfg.n_layers),
        ),
    )
    return _last_valid_logits(params, cfg, x, blk_len), gen_k, gen_v


def forward_decode_buffered(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B] int32 — one new token per slot
    positions: jax.Array,  # [B] ABSOLUTE position of that token
    k_own: jax.Array,  # own-token KV, layout per own_impl (see below)
    v_own: jax.Array,
    own_lens: jax.Array,  # [B] valid own tokens (chunk-start lengths)
    chunk_k: jax.Array,  # [L, B, n_steps, n_kv, hd] — this chunk's new KV
    chunk_v: jax.Array,
    tail_len: jax.Array,  # [B] entries already in the chunk buffer
    prefix_k_all: jax.Array,  # [L, Sp, n_kv, hd] shared dense prefix
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,  # scalar int32
    page_tables: jax.Array | None = None,  # [B, P] (own_impl="pallas" only)
    own_impl: str = "dense",  # static: "dense" pre-gathered | "pallas" kernel
    shmap: Any = None,  # static ops.attention.ShardedAttnImpl | None —
    # wraps the paged kernel in shard_map over the tp kv-head axis
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step against (prefix | own tokens | chunk buffer).

    The fused-chunk fast path (engine/engine.py): per-step K/V appends go to
    a small dense chunk buffer instead of the big paged cache — the paged
    scatter measured ~1.8 ms/step on this size class vs ~0.05 ms for the
    buffer append; the engine flushes the buffer to pages ONCE per chunk.
    Attention is a 3-part cascade merged exactly via log-sum-exp:
      A. shared dense prefix (read once for the whole batch),
      B. the slot's own tokens — own_impl="dense": pre-gathered dense KV
         [L, B, L_own, n_kv, hd] frozen for the chunk; own_impl="pallas":
         the paged caches [L, num_pages, ps, n_kv, hd] + page_tables,
         streamed page-by-page by the Pallas kernel
         (ops/pallas_paged_attention.paged_decode_attention_parts) with no
         materialized gather,
      C. the chunk buffer (this chunk's tokens, including the current one).
    Returns (logits [B,V] f32, chunk_k, chunk_v).
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    n_steps = chunk_k.shape[2]
    inv_freq = rope_inv_freq(cfg)
    if own_impl == "pallas":
        from k8s_llm_scheduler_tpu.ops.pallas_paged_attention import (
            paged_decode_attention_parts,
            paged_decode_attention_parts_shmap,
        )

        if shmap is not None:
            def paged_parts(q, ko, vo, pt, lens):
                return paged_decode_attention_parts_shmap(
                    q, ko, vo, pt, lens, shmap.mesh, shmap.axis
                )
        else:
            paged_parts = paged_decode_attention_parts

    x = params["embed"][tokens]  # [B, D]
    layer_ids = jnp.arange(cfg.n_layers)
    q_per_kv = cfg.q_per_kv
    row = jnp.arange(B)

    Sp = prefix_k_all.shape[1]
    pre_mask = (jnp.arange(Sp) < prefix_len)[None, None, None, :]
    if own_impl == "dense":
        L_own = k_own.shape[2]
        own_mask = (jnp.arange(L_own)[None, :] < own_lens[:, None])[:, None, None, :]
    # current token attends itself: include the entry written this step
    tail_mask = (jnp.arange(n_steps)[None, :] <= tail_len[:, None])[:, None, None, :]

    def body(carry, xs):
        x, ck, cv = carry
        lp, pk, pv, ko, vo, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bd,dh->bh").reshape(B, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bd,dh->bh").reshape(B, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bd,dh->bh").reshape(B, cfg.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)

        ck = ck.at[idx, row, tail_len].set(k.astype(ck.dtype))
        cv = cv.at[idx, row, tail_len].set(v.astype(cv.dtype))

        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(B, cfg.n_kv_heads, q_per_kv, hd)
        if own_impl == "pallas":
            own_part = paged_parts(q, ko, vo, page_tables, own_lens)
        else:
            own_part = attend_part(qg, ko, vo, own_mask, "bkgh,blkh->bkgl")
        parts = [
            attend_part(qg, pk, pv, pre_mask, "bkgh,skh->bkgs"),
            own_part,
            attend_part(qg, ck[idx], cv[idx], tail_mask, "bkgh,blkh->bkgl"),
        ]
        attn = merge_attention_parts(parts).reshape(B, cfg.n_heads * hd).astype(x.dtype)
        attn = _dense(attn, lp["wo"], "bh,hd->bd")
        x = x + attn
        x = x + _mlp(lp, cfg, x)
        return (x, ck, cv), None

    (x, chunk_k, chunk_v), _ = jax.lax.scan(
        body, (x, chunk_k, chunk_v),
        (params["layers"], prefix_k_all, prefix_v_all, k_own, v_own, layer_ids),
    )
    return _logits(params, cfg, x), chunk_k, chunk_v


def forward_decode_fused_body(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,
    positions: jax.Array,
    k_own: jax.Array,
    v_own: jax.Array,
    own_lens: jax.Array,
    chunk_k: jax.Array,
    chunk_v: jax.Array,
    tail_len: jax.Array,
    prefix_k_all: jax.Array,
    prefix_v_all: jax.Array,
    prefix_len: jax.Array,
    page_tables: jax.Array | None = None,
    own_impl: str = "dense",
    shmap: Any = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The fused decode loop's BODY forward (engine/fused/loop.py).

    Identical math to `forward_decode_buffered` — the one-step cascade
    the chunked scan runs — re-exported under the fused loop's contract so
    the two decode paths provably share one forward (greedy fused ==
    chunked token identity rests on this being the SAME function, not a
    lookalike):

    - every array keeps a STATIC shape across iterations (`tail_len` is
      the only induction input; the chunk buffer is preallocated at the
      chunk length), which is what lets `lax.while_loop` carry the state
      without re-tracing;
    - the frozen own-page KV (`k_own`/`v_own`) is closed over by the loop
      body as a while_loop constant — the gather happens once per chunk
      outside the loop, never per iteration;
    - per-step K/V lands in the chunk buffer at `tail_len`, so the fused
      loop's post-exit page flush sees exactly the layout the chunked
      path's flush was written for.
    """
    return forward_decode_buffered(
        params, cfg, tokens, positions, k_own, v_own, own_lens,
        chunk_k, chunk_v, tail_len, prefix_k_all, prefix_v_all, prefix_len,
        page_tables=page_tables, own_impl=own_impl, shmap=shmap,
    )


# ------------------------------------------------------------------- decode
def forward_decode(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B] int32 — one new token per slot
    positions: jax.Array,  # [B] 0-indexed position of the new token
    k_cache: jax.Array,  # [L, num_pages, page_size, n_kv, hd]
    v_cache: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    active: jax.Array,  # [B] bool — inactive slots neither write nor matter
    paged_attn: str = "xla",  # static: "xla" gather path | "pallas" kernel
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One autoregressive decode step over the paged KV cache.

    Scatters the new token's K/V into the cache pages, attends over all
    cached tokens (including the new one), returns (logits [B,V] f32,
    k_cache, v_cache). Pass caches as donated args under jit so updates
    happen in place. paged_attn="pallas" swaps the gather-then-attend XLA
    path for the streaming Pallas kernel
    (ops/pallas_paged_attention.py); must be static under jit.
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    page_size = k_cache.shape[2]
    inv_freq = rope_inv_freq(cfg)

    if paged_attn == "pallas":
        from k8s_llm_scheduler_tpu.ops.pallas_paged_attention import (
            paged_decode_attention_pallas,
        )

        attn_kernel = paged_decode_attention_pallas
    else:
        attn_kernel = paged_decode_attention

    page_slot = positions // page_size  # which entry of the page table
    page_ids = jnp.take_along_axis(page_tables, page_slot[:, None], axis=1)[:, 0]
    offsets = positions % page_size
    # Inactive slots must not write through their (possibly recycled) page
    # table — redirect them to page 0, which the KV cache manager reserves
    # as scratch and never allocates to a sequence.
    page_ids = jnp.where(active, page_ids, 0)
    offsets = jnp.where(active, offsets, 0)
    seq_lens = positions + 1

    x = params["embed"][tokens]  # [B, D]

    def body(carry, lp_with_idx):
        x, kc, vc = carry
        lp, idx = lp_with_idx
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bd,dh->bh").reshape(B, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bd,dh->bh").reshape(B, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bd,dh->bh").reshape(B, cfg.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)

        # Scatter new K/V into this layer's pages (inactive slots were
        # redirected to the reserved scratch page 0 above).
        layer_k = kc[idx]
        layer_v = vc[idx]
        layer_k = layer_k.at[page_ids, offsets].set(k)
        layer_v = layer_v.at[page_ids, offsets].set(v)
        kc = jax.lax.dynamic_update_index_in_dim(kc, layer_k, idx, axis=0)
        vc = jax.lax.dynamic_update_index_in_dim(vc, layer_v, idx, axis=0)

        attn = attn_kernel(q, layer_k, layer_v, page_tables, seq_lens)
        attn = _dense(attn.reshape(B, cfg.n_heads * hd), lp["wo"], "bh,hd->bd")
        x = x + attn
        x = x + _mlp(lp, cfg, x)
        return (x, kc, vc), None

    layer_ids = jnp.arange(cfg.n_layers)
    (x, k_cache, v_cache), _ = jax.lax.scan(
        body, (x, k_cache, v_cache), (params["layers"], layer_ids)
    )
    return _logits(params, cfg, x), k_cache, v_cache


# ------------------------------------------------ hidden-transfer head
def init_hidden_transfer(rng: jax.Array, cfg: LlamaConfig, k: int) -> Params:
    """Random-init a hidden-transfer multi-token prediction head
    (*Hidden Transfer*, PAPERS.md): `k` per-offset transfer matrices
    [k, D, D] applied RESIDUALLY to the target's final-layer hidden state
    — x_h = x + x @ T_h — then pushed through the model's OWN final norm
    and LM head (no second vocab projection to train or store).

    Init is small (0.02/sqrt(D)) so x_h ~= x at step 0: the untrained
    head predicts roughly the current position's distribution for every
    future offset — a sane warm start for train/hidden.py, and never a
    correctness hazard (the spec verifier accepts only target-consistent
    tokens regardless of what the head proposes).
    """
    if k < 1:
        raise ValueError(f"hidden-transfer k must be >= 1, got {k}")
    D = cfg.d_model
    scale = 0.02 * D**-0.5
    t = (
        jax.random.normal(rng, (k, D, D), dtype=jnp.float32) * scale
    ).astype(cfg.dtype)
    return {"transfer": t}


def hidden_transfer_hidden(ht: Params, x: jax.Array, h: int) -> jax.Array:
    """Pseudo hidden state for future offset `h` (0-based head index):
    x [..., D] -> x + x @ T_h. The caller runs _logits on the result."""
    return x + _dense(x, ht["transfer"][h], "...d,de->...e")


def hidden_transfer_logits(
    params: Params, cfg: LlamaConfig, ht: Params, x: jax.Array
) -> jax.Array:
    """All heads' logits from one hidden state: x [..., D] ->
    [..., k, V]. Training (train/hidden.py) and the fused verify+propose
    program (spec/hidden.py) share this exact math."""
    xs = jnp.stack(
        [
            hidden_transfer_hidden(ht, x, h)
            for h in range(ht["transfer"].shape[0])
        ],
        axis=-2,
    )  # [..., k, D]
    return _logits(params, cfg, xs)


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
