"""Checkpoint loading: HF safetensors -> sharded JAX params, orbax native.

The reference never loads weights — its 70B lives behind the HuggingFace
API (reference scheduler.py:425, config.yaml:8). Self-hosting the decision
LLM makes weight loading a real subsystem (SURVEY §5 checkpoint/resume;
§7 hard part #1: 70B into a TP mesh without host-RAM blowups):

- **Streaming HF import**: `load_hf_checkpoint` walks the model's
  safetensors shard files tensor by tensor. Each per-layer tensor is
  transposed to this framework's [in, out] einsum layout and written
  straight into its stacked parameter's DEVICE buffer — preallocated
  sharded on the mesh, updated in place via a donated
  dynamic_update_index_in_dim. Peak host memory is ONE LAYER tensor
  (~0.5 GB for the 70B MLP matrix in bf16), never a stacked parameter
  and never the model: HF shards interleave parameter kinds, so
  accumulating stacked host buffers would approach the full 140 GB.
- **Direct-to-shard placement**: with a mesh + PartitionSpecs
  (parallel/sharding.py), layer slices and top-level tensors are placed
  via `jax.device_put(x, NamedSharding(mesh, spec))` — XLA slices the
  host array straight onto the devices; nothing is replicated on host.
- **Native checkpoints**: orbax save/restore of the params pytree for
  fast resume (resharding happens at restore via the same specs).

HF -> framework tensor map (Llama 3.x family):
  model.embed_tokens.weight            -> embed                [V, D]
  model.layers.{i}.input_layernorm     -> layers.attn_norm[i]  [D]
  model.layers.{i}.self_attn.q_proj    -> layers.wq[i]         [D, H*hd] (T)
  model.layers.{i}.self_attn.k_proj    -> layers.wk[i]         [D, KV*hd] (T)
  model.layers.{i}.self_attn.v_proj    -> layers.wv[i]         [D, KV*hd] (T)
  model.layers.{i}.self_attn.o_proj    -> layers.wo[i]         [H*hd, D] (T)
  model.layers.{i}.post_attention_layernorm -> layers.mlp_norm[i]
  model.layers.{i}.mlp.gate_proj       -> layers.w_gate[i]     [D, F] (T)
  model.layers.{i}.mlp.up_proj         -> layers.w_up[i]       [D, F] (T)
  model.layers.{i}.mlp.down_proj       -> layers.w_down[i]     [F, D] (T)
  model.norm.weight                    -> final_norm           [D]
  lm_head.weight                       -> lm_head              [D, V] (T)
  (lm_head absent => tie_embeddings; HF rotary is half-split, matching
   models/llama.apply_rope — no permutation needed.)
"""

from __future__ import annotations

import json
import logging
import os
import re
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import Params
from k8s_llm_scheduler_tpu.parallel.sharding import param_specs

logger = logging.getLogger(__name__)

from k8s_llm_scheduler_tpu.models.quant import (  # noqa: E402
    QUANT_KEYS as _QUANT_KEYS,
    _quantize_weight_donated as _quantize_donated,
)

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)\.weight$")

# HF suffix -> (param key under "layers", transpose?)
_LAYER_MAP = {
    "input_layernorm": ("attn_norm", False),
    "self_attn.q_proj": ("wq", True),
    "self_attn.k_proj": ("wk", True),
    "self_attn.v_proj": ("wv", True),
    "self_attn.o_proj": ("wo", True),
    "post_attention_layernorm": ("mlp_norm", False),
    "mlp.gate_proj": ("w_gate", True),
    "mlp.up_proj": ("w_up", True),
    "mlp.down_proj": ("w_down", True),
}

_TOP_MAP = {
    "model.embed_tokens.weight": ("embed", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),
}


def _expected_shapes(cfg: LlamaConfig) -> dict[str, tuple[int, ...]]:
    hd = cfg.head_dim
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    shapes = {
        "embed": (cfg.vocab_size, D),
        "final_norm": (D,),
        "layers.attn_norm": (L, D),
        "layers.wq": (L, D, cfg.n_heads * hd),
        "layers.wk": (L, D, cfg.n_kv_heads * hd),
        "layers.wv": (L, D, cfg.n_kv_heads * hd),
        "layers.wo": (L, cfg.n_heads * hd, D),
        "layers.mlp_norm": (L, D),
        "layers.w_gate": (L, D, F),
        "layers.w_up": (L, D, F),
        "layers.w_down": (L, F, D),
    }
    if not cfg.tie_embeddings:
        shapes["lm_head"] = (D, cfg.vocab_size)
    return shapes


def _flat_specs(cfg: LlamaConfig, tp: str | None, fsdp: str | None):
    specs = param_specs(cfg, tp=tp, fsdp=fsdp)
    flat = {"embed": specs["embed"], "final_norm": specs["final_norm"]}
    for k, v in specs["layers"].items():
        flat[f"layers.{k}"] = v
    if "lm_head" in specs:
        flat["lm_head"] = specs["lm_head"]
    return flat


def checkpoint_files(path: str | Path) -> list[Path]:
    """The safetensors shards of an HF checkpoint dir, index-ordered."""
    path = Path(path)
    index = path / "model.safetensors.index.json"
    if index.exists():
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return [path / name for name in sorted(set(weight_map.values()))]
    single = path / "model.safetensors"
    if single.exists():
        return [single]
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors files under {path}")
    return files


def load_hf_checkpoint(
    path: str | Path,
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
    *,
    tp: str | None = "tp",
    fsdp: str | None = None,
    dtype: Any | None = None,
    quantize: str | None = None,
) -> Params:
    """Stream an HF Llama safetensors checkpoint into (sharded) JAX params.

    Walks shard files tensor by tensor. Each per-layer tensor is written
    STRAIGHT into its stacked parameter's device buffer (allocated sharded
    on the mesh up front; the write is a donated
    dynamic_update_index_in_dim, so it is in-place) — host peak is ONE
    LAYER tensor, not a stacked parameter. HF shard files interleave the
    parameter kinds, so accumulating stacked host buffers per kind would
    hold nearly the whole model in host RAM at 70B scale (~140 GB).
    """
    from safetensors import safe_open

    dtype = dtype or cfg.dtype
    shapes = _expected_shapes(cfg)
    flat_specs = _flat_specs(cfg, tp, fsdp)

    def place(name: str, host: np.ndarray | jax.Array) -> jax.Array:
        if mesh is not None:
            return jax.device_put(host, NamedSharding(mesh, flat_specs[name]))
        return jnp.asarray(host)

    def alloc(name: str) -> jax.Array:
        if mesh is not None:
            return jax.jit(
                lambda: jnp.zeros(shapes[name], dtype),
                out_shardings=NamedSharding(mesh, flat_specs[name]),
            )()
        return jnp.zeros(shapes[name], dtype)

    set_layer = jax.jit(  # graftlint: ok[donated-buffer-escape] — pure index update: in/out shardings are identical by construction, so XLA aliases the donation without a bundle
        lambda buf, x, i: jax.lax.dynamic_update_index_in_dim(buf, x, i, 0),
        donate_argnums=(0,),
    )

    filled: dict[str, int] = {}
    out_flat: dict[str, jax.Array] = {}

    for file in checkpoint_files(path):
        with safe_open(str(file), framework="np") as f:
            for hf_name in f.keys():
                m = _LAYER_RE.match(hf_name)
                if m:
                    layer, suffix = int(m.group(1)), m.group(2)
                    if suffix not in _LAYER_MAP:
                        logger.warning("skipping unknown tensor %s", hf_name)
                        continue
                    key, transpose = _LAYER_MAP[suffix]
                    name = f"layers.{key}"
                    if layer >= cfg.n_layers:
                        raise ValueError(
                            f"{hf_name}: layer {layer} >= n_layers={cfg.n_layers}"
                        )
                    tensor = f.get_tensor(hf_name)
                    if transpose:
                        tensor = np.ascontiguousarray(tensor.T)
                    if tensor.shape != shapes[name][1:]:
                        raise ValueError(
                            f"{hf_name}: shape {tensor.shape} != expected "
                            f"{shapes[name][1:]}"
                        )
                    if name not in out_flat:
                        out_flat[name] = alloc(name)
                        filled[name] = 0
                    host = _cast(tensor, dtype)
                    if mesh is not None:
                        spec = flat_specs[name]
                        slice_spec = P(*spec[1:]) if len(spec) > 1 else P()
                        dev = jax.device_put(
                            host, NamedSharding(mesh, slice_spec)
                        )
                    else:
                        dev = jnp.asarray(host)
                    out_flat[name] = set_layer(
                        out_flat[name], dev, jnp.int32(layer)
                    )
                    filled[name] += 1
                    if (
                        quantize == "int8"
                        and filled[name] == cfg.n_layers
                        and name.split(".", 1)[1] in _QUANT_KEYS
                    ):
                        out_flat[name] = _quantize_donated(out_flat[name])
                elif hf_name in _TOP_MAP:
                    name, transpose = _TOP_MAP[hf_name]
                    if name == "lm_head" and cfg.tie_embeddings:
                        logger.info("ignoring lm_head (tied embeddings)")
                        continue
                    tensor = f.get_tensor(hf_name)
                    if transpose:
                        tensor = np.ascontiguousarray(tensor.T)
                    if tensor.shape != shapes[name]:
                        raise ValueError(
                            f"{hf_name}: shape {tensor.shape} != expected {shapes[name]}"
                        )
                    out_flat[name] = place(name, _cast(tensor, dtype))
                else:
                    logger.warning("skipping unknown tensor %s", hf_name)

    missing = set(shapes) - set(out_flat)
    partial = {
        n: f"{filled[n]}/{cfg.n_layers}"
        for n in filled
        if filled[n] < cfg.n_layers
    }
    if missing or partial:
        raise ValueError(
            f"checkpoint incomplete: missing {sorted(missing)}"
            + (f"; partial layer stacks {partial}" if partial else "")
        )

    params: Params = {
        "embed": out_flat["embed"],
        "final_norm": out_flat["final_norm"],
        "layers": {
            k.split(".", 1)[1]: v
            for k, v in out_flat.items()
            if k.startswith("layers.")
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = out_flat["lm_head"]
    return params


def _cast(host: np.ndarray, dtype) -> np.ndarray:
    """Cast a host buffer to the target dtype HOST-SIDE (ml_dtypes handles
    bf16 in numpy). Staying on host matters: the only device transfer must
    be place()'s sharded device_put — routing through jnp.asarray here would
    commit the full stacked parameter to one device and OOM it at 70B."""
    import ml_dtypes

    if dtype == jnp.bfloat16:
        target = np.dtype(ml_dtypes.bfloat16)
    else:
        target = np.dtype(jnp.dtype(dtype).name)
    return host.astype(target, copy=False)


class CheckpointError(RuntimeError):
    """A native checkpoint is missing, torn, or shaped for another config.

    Raised by restore_checkpoint's pre-validation with the offending path
    and the FIRST mismatched param — instead of the deep orbax/tensorstore
    stack trace the raw restore produces for the same faults."""


# ------------------------------------------------------------------ orbax
def _fsync_tree(root: Path) -> None:
    """fsync every file and directory under `root` (and `root` itself):
    a rename is only crash-safe once the renamed tree's CONTENT is on
    disk — rename-then-crash with dirty pages can leave a torn tree
    under the final name, which is exactly the window save_checkpoint's
    bare renames used to carry (the registry's write-aside discipline,
    rollout/registry.py, fsyncs before every publish rename)."""
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in filenames:
            fd = os.open(os.path.join(dirpath, fname), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        fd = os.open(dirpath, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def save_checkpoint(path: str | Path, params: Params) -> None:
    """Write a native orbax checkpoint of the params pytree (overwrites —
    orbax's default refuses an existing dir AFTER a full training run has
    already been spent).

    ATOMIC against crashes: orbax's force=True DELETES the existing dir
    before writing, so a save that wedges mid-transfer (measured on the
    tunneled bench host) would destroy the only snapshot a --resume run
    depends on. Write aside, fsync the staged tree, then swap — the
    fsync matters as much as the rename order: a crash between a bare
    rename and writeback would leave a TORN tree under the active name
    (the durability round's journal/registry discipline, now here
    too). The previous checkpoint survives as `.old` until the new one
    is durably in place."""
    import shutil

    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    tmp = path.with_name(path.name + ".saving")
    if tmp.exists():
        shutil.rmtree(tmp)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(tmp, params, force=True)
        ckptr.wait_until_finished()
    _fsync_tree(tmp)
    old = path.with_name(path.name + ".old")
    if old.exists():
        shutil.rmtree(old)
    if path.exists():
        os.rename(path, old)
    os.rename(tmp, path)
    # make both renames durable before dropping the only fallback copy
    fd = os.open(path.parent, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    if old.exists():
        shutil.rmtree(old)


def restore_checkpoint(
    path: str | Path,
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
    *,
    tp: str | None = "tp",
    fsdp: str | None = None,
) -> Params:
    """Restore a native orbax checkpoint, resharded onto `mesh` (or one
    host device). Restoration is direct-to-shard: orbax reads only each
    device's slice of every parameter.

    Pre-validates before touching orbax's restore path: a missing dir, a
    partial/torn checkpoint (no orbax metadata), or a stored tree whose
    shapes don't match `cfg` raises CheckpointError naming the path and
    the first mismatched param — not a tensorstore traceback."""
    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    if not path.exists():
        raise CheckpointError(f"checkpoint dir {path} does not exist")
    if not path.is_dir():
        raise CheckpointError(f"checkpoint path {path} is not a directory")
    if not any((path / marker).exists() for marker in ("_METADATA", "_CHECKPOINT_METADATA")):
        raise CheckpointError(
            f"{path} is not an orbax checkpoint (no _METADATA — partial or "
            f"torn save, or an HF safetensors dir passed to the native "
            f"restore path)"
        )
    shapes = _expected_shapes(cfg)
    flat_specs = _flat_specs(cfg, tp, fsdp)

    def abstract(name: str):
        if mesh is not None:
            sharding = NamedSharding(mesh, flat_specs[name])
        else:
            sharding = None
        return jax.ShapeDtypeStruct(shapes[name], cfg.dtype, sharding=sharding)

    target: Params = {
        "embed": abstract("embed"),
        "final_norm": abstract("final_norm"),
        "layers": {
            name.split(".", 1)[1]: abstract(name)
            for name in shapes
            if name.startswith("layers.")
        },
    }
    if not cfg.tie_embeddings:
        target["lm_head"] = abstract("lm_head")
    with ocp.StandardCheckpointer() as ckptr:
        _validate_stored_shapes(ckptr, path, cfg, shapes)
        try:
            return ckptr.restore(path, target)
        except Exception as exc:
            raise CheckpointError(
                f"restore of {path} failed for config {cfg.name!r}: {exc}"
            ) from exc


def _validate_stored_shapes(ckptr, path: Path, cfg: LlamaConfig, shapes) -> None:
    """Compare the stored tree's metadata against the config's expected
    shapes; raise CheckpointError on the first mismatch or missing param."""
    try:
        meta = ckptr.metadata(path)
    except Exception:
        # metadata unreadable on this orbax version/layout: fall through to
        # restore, whose failures are wrapped in CheckpointError anyway
        return

    def lookup(name: str):
        node = meta
        for part in name.split("."):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        return node

    for name in sorted(shapes):
        leaf = lookup(name)
        if leaf is None:
            raise CheckpointError(
                f"{path}: checkpoint is missing param {name!r} expected by "
                f"config {cfg.name!r}"
            )
        stored = tuple(getattr(leaf, "shape", ()) or ())
        if stored and stored != tuple(shapes[name]):
            raise CheckpointError(
                f"{path}: param {name!r} has shape {stored}, but config "
                f"{cfg.name!r} expects {tuple(shapes[name])} — the "
                f"checkpoint was trained for a different config"
            )
