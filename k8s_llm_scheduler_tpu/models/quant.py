"""Weight-only int8 quantization for the decision model.

Why this exists (BASELINE.md config 2): Llama-3.1-8B in bf16 is ~16 GB of
weights — it does not fit a single v5e chip (16 GB HBM) next to KV buffers
and activations. Per-channel int8 weight storage halves that to ~8 GB and
halves the weight HBM traffic that dominates decode steps; activations stay
bf16 and the dequantize is fused by XLA into the matmul (the int8->bf16
convert happens in registers feeding the MXU, never materialized).

Scheme: symmetric per-output-channel. For a stacked weight [L, in, out]:
    scale[L, 1, out] = max(|w|) over `in` / 127
    q[L, in, out]    = round(w / scale)  (int8)
Matmuls compute einsum(x, q.astype(x.dtype)) * scale — the scale multiply
broadcasts over the output channel, preserving each channel's dynamic
range (the reason per-channel beats per-tensor at zero runtime cost).

The quantized pytree swaps each dense weight leaf for {"q": int8,
"scale": f32}; models/llama._dense dispatches on that shape, so every
forward path (prefill, suffix cascade, waves, chunked decode) runs
quantized without further changes. Training stays full-precision —
quantize at serving time (build_local_backend(quantize="int8")).

The reference has no quantization surface at all — its model capacity
decisions live server-side behind the HF API (SURVEY §2.3).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# Dense weight leaves that quantize (stacked [L, in, out] / [L, out, in]).
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8 quantization of [..., in, out]."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0  # [..., 1, out]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


# Donated form: the bf16 source buffer is released as its int8+scale pair
# materializes, so quantizing never needs source + result resident together.
_quantize_weight_donated = jax.jit(quantize_weight, donate_argnums=(0,))


def quantize_params(params: Params) -> Params:
    """Quantize the transformer's dense weights; embed/norms stay as-is.

    Weight-by-weight with donation — peak device memory is the int8 model
    plus ONE bf16 weight, not bf16 + int8 models side by side (8B bf16
    alone is ~16 GB, the whole v5e; a tree-level jit would OOM before the
    first int8 byte lands). Sharded inputs produce identically-sharded
    outputs (elementwise + per-channel reduction — GSPMD keeps layouts).
    """
    out = dict(params)
    layers = dict(params["layers"])
    for key in QUANT_KEYS:
        layers[key] = _quantize_weight_donated(layers[key])
    out["layers"] = layers
    return out


def init_params_int8_host(rng_seed: int, cfg) -> Params:
    """Random-init an int8-quantized model HOST-SIDE, shipping only int8.

    The device-side quantized init (init_params(quantize="int8")) still
    materializes each bf16 weight on device before donating it away — a
    ~3.8 GB transient for the 8B stacked MLP matrix, which together with
    the accumulating int8 model overflows a 16 GB chip. Here the random
    weights never exist in bf16 on device at all: numpy generates and
    quantizes per channel on host, and only the int8 tensors (+ f32
    scales + bf16 embed/norms) transfer. Peak device memory = the final
    quantized model.
    """
    import numpy as np

    import jax.numpy as _jnp

    rng = np.random.default_rng(rng_seed)
    hd = cfg.head_dim
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff

    def host_quant(shape, in_dim):
        scale_init = in_dim**-0.5
        out = {}
        # per-layer to bound host transients at one layer slice
        qs, ss = [], []
        for _ in range(shape[0]):
            w = rng.standard_normal(shape[1:], dtype=np.float32) * scale_init
            s = np.maximum(np.abs(w).max(axis=-2, keepdims=True) / 127.0, 1e-12)
            qs.append(np.clip(np.round(w / s), -127, 127).astype(np.int8))
            ss.append(s.astype(np.float32))
        out["q"] = jnp.asarray(np.stack(qs))
        out["scale"] = jnp.asarray(np.stack(ss))
        return out

    def norm(shape):
        return jnp.ones(shape, dtype=cfg.dtype)

    embed = (rng.standard_normal((cfg.vocab_size, D), dtype=np.float32) * 0.02)
    params: Params = {
        "embed": jnp.asarray(embed).astype(cfg.dtype),
        "final_norm": norm((D,)),
        "layers": {
            "attn_norm": norm((L, D)),
            "wq": host_quant((L, D, cfg.n_heads * hd), D),
            "wk": host_quant((L, D, cfg.n_kv_heads * hd), D),
            "wv": host_quant((L, D, cfg.n_kv_heads * hd), D),
            "wo": host_quant((L, cfg.n_heads * hd, D), cfg.n_heads * hd),
            "mlp_norm": norm((L, D)),
            "w_gate": host_quant((L, D, F), D),
            "w_up": host_quant((L, D, F), D),
            "w_down": host_quant((L, F, D), F),
        },
    }
    if not cfg.tie_embeddings:
        lm = rng.standard_normal((D, cfg.vocab_size), dtype=np.float32) * D**-0.5
        params["lm_head"] = jnp.asarray(lm).astype(cfg.dtype)
    del _jnp
    return params


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "scale" in w


def param_bytes(params: Params) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params)
    )
