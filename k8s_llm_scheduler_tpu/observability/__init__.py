"""Metrics endpoint and per-phase tracing."""
