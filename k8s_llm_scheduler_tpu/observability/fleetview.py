"""Fleet telemetry aggregation: N replicas' telemetry merged into one view.

PR 6 made serving fleet-scale (sharded FleetReplicas, disaggregated
pools, replica wire) but telemetry stayed strictly per-process: 16
replicas meant 16 /metrics endpoints, 16 flight recorders, and no answer
to "what is the FLEET p99?". This module is the fan-in:

- `build_telemetry` renders one replica's pullable payload: its stats
  tree (histograms ride along as the embedded HIST_KEY bucket dicts),
  a since-cursor slice of its flight recorder (bounded by trace count
  AND bytes — FlightRecorder.export_slices), and its engine-sampler ring.
  This is what the `telemetry_pull` replica-wire op ships
  (sched/replica.py) and what in-process FleetReplicas serve directly.
- `FleetAggregator` polls N sources (remote ReplicaClients, in-process
  replicas, or anything callable), keeps per-source cursors, and merges:
  - **histograms** bucket-by-bucket — every PhaseRecorder shares the
    fixed process-wide bucket ladder (observability/trace.BUCKET_BOUNDS_S)
    precisely so two replicas' "decide" histograms ADD, and fleet
    p50/p95/p99 falls out of `hist_percentiles` over the summed counts
    (identical, within one bucket width, to recomputing from the raw
    samples — the merge loses nothing the bucketing hadn't already lost);
  - **counters** by summation (they are monotone counts);
  - **traces** by trace id: the ids already ride decision frames across
    the replica wire, so a coordinator-side decision trace and the
    worker-side `replica.decide` trace stitch into one span set here.
- Failure semantics: a replica that dies mid-pull degrades the view to
  the surviving members — its last-known payload is retained and marked
  STALE (with age), never silently dropped and never blocking the round.
  A replica joining mid-scrape simply contributes its partial (shorter)
  history; cumulative histograms make that sound by construction.

`FleetAggregator.render_prometheus()` emits ONE merged exposition
(observability/metrics.render_prometheus over the merged tree), and
`render_top` is the text frame behind `cli fleet top`.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable

from k8s_llm_scheduler_tpu.observability.trace import (
    BUCKET_BOUNDS_S,
    HIST_KEY,
    hist_percentiles,
)

logger = logging.getLogger(__name__)

_N_BUCKETS = len(BUCKET_BOUNDS_S) + 1

# Defaults for one telemetry_pull frame: bounded so a 16-replica round
# never ships unbounded JSONL (the same caps /debug/* enforce).
DEFAULT_MAX_TRACES = 256
DEFAULT_MAX_BYTES = 1 << 20


def build_telemetry(
    stats: dict[str, Any],
    recorder: Any = None,
    sampler: Any = None,
    *,
    since_seq: int = 0,
    max_traces: int = DEFAULT_MAX_TRACES,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> dict[str, Any]:
    """One replica's pullable telemetry payload (wire-shaped: plain JSON
    types only)."""
    # Hoist the resident-loop gauge family (engine.get_stats nests it
    # under "engine") to the payload top level so the fleet merge and
    # `render_prometheus` expose it as llm_scheduler_persistent_* — the
    # SAME family name the per-replica /metrics mounts (metrics.py), so
    # dashboards need one query whichever endpoint they scrape.
    eng = stats.get("engine")
    if (
        isinstance(eng, dict)
        and isinstance(eng.get("persistent"), dict)
        and "persistent" not in stats
    ):
        stats = {**stats, "persistent": eng["persistent"]}
    out: dict[str, Any] = {
        "stats": stats,
        "traces": [],
        "next_cursor": since_seq,
        "truncated": False,
        "recorded_total": 0,
    }
    if recorder is not None:
        entries, next_cursor, truncated = recorder.export_slices(
            since_seq=since_seq, max_traces=max_traces, max_bytes=max_bytes,
        )
        out["traces"] = entries
        out["next_cursor"] = next_cursor
        out["truncated"] = truncated
        out["recorded_total"] = recorder.seq
    if sampler is not None:
        out["sampler"] = sampler.series()
    return out


def _merge_hist_stat(entries: list[dict]) -> dict:
    """Merge same-phase stat dicts (PhaseRecorder.snapshot leaf shape):
    buckets sum, derived fields recompute from the MERGED buckets."""
    counts = [0] * _N_BUCKETS
    sum_s = 0.0
    total_n = 0
    max_ms = 0.0
    for entry in entries:
        hist = entry.get(HIST_KEY) or {}
        ec = hist.get("counts") or []
        if len(ec) != _N_BUCKETS:
            continue  # foreign bucket ladder: refuse to merge garbage
        for i, c in enumerate(ec):
            counts[i] += int(c)
        sum_s += float(hist.get("sum_s", 0.0))
        total_n += int(hist.get("count", 0))
        max_ms = max(max_ms, float(entry.get("max_ms", 0.0)))
    p50, p95, p99 = hist_percentiles(counts)
    return {
        "count": total_n,
        "total_ms": sum_s * 1000.0,
        "avg_ms": (sum_s / total_n) * 1000.0 if total_n else 0.0,
        "max_ms": max_ms,
        "p50_ms": p50,
        "p95_ms": p95,
        "p99_ms": p99,
        HIST_KEY: {"counts": counts, "sum_s": sum_s, "count": total_n},
    }


# Numeric leaves that are NOT summable counters. `generation` is an epoch
# shared through the fleet's single L2 (fleet/cache.py) — every replica
# reports the same authority value, so the fleet figure is the max, not
# N times it. Ratio leaves (`*_rate`, `*_frac`) are per-replica derived
# values; the merged view reports their mean (the exact fleet ratio needs
# the underlying counters, which ARE summed wherever the tree carries
# them).
_EPOCH_LEAVES = frozenset({"generation", "known_generation"})
# `*_per_decision` is a derived per-replica ratio like the others —
# summing N replicas' dispatches_per_decision would report a fleet that
# pays N times the per-decision cost it actually does.
_RATIO_SUFFIXES = ("_rate", "_frac", "_per_decision")


def _merge_stats(trees: list[dict]) -> dict:
    """Recursive fleet merge of stats trees: histogram-bearing dicts merge
    bucket-wise, plain dicts merge by key union, numeric leaves SUM
    (nearly every numeric leaf in the stats contract is a monotone counter
    or a count; the exceptions — shared epochs and derived ratios, see
    _EPOCH_LEAVES/_RATIO_SUFFIXES — merge by max and mean). Strings keep
    the first value when all agree, else a "mixed" marker; lists are
    dropped (the per-replica view keeps them)."""
    trees = [t for t in trees if isinstance(t, dict)]
    if not trees:
        return {}
    if any(isinstance(t.get(HIST_KEY), dict) for t in trees):
        return _merge_hist_stat(trees)
    out: dict[str, Any] = {}
    keys: list[str] = []
    seen = set()
    for t in trees:
        for k in t:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    for key in keys:
        values = [t[key] for t in trees if key in t]
        if any(isinstance(v, dict) for v in values):
            out[key] = _merge_stats([v for v in values if isinstance(v, dict)])
        elif all(isinstance(v, bool) for v in values):
            out[key] = any(values)
        elif all(isinstance(v, (int, float)) for v in values):
            if key in _EPOCH_LEAVES:
                out[key] = max(values)
            elif key.endswith(_RATIO_SUFFIXES):
                out[key] = round(sum(values) / len(values), 6)
            else:
                total = sum(values)
                out[key] = (
                    round(total, 6) if isinstance(total, float) else total
                )
        elif all(isinstance(v, str) for v in values):
            out[key] = values[0] if len(set(values)) == 1 else "mixed"
        # lists/None: dropped from the merged view
    return out


class _SourceState:
    __slots__ = (
        "pull", "cursor", "stats", "traces", "sampler", "last_ok_t",
        "failures", "stale", "pulls",
    )

    def __init__(self, pull: Callable[[int], dict]) -> None:
        self.pull = pull
        self.cursor = 0
        self.stats: dict = {}
        self.traces: deque[dict] = deque(maxlen=DEFAULT_MAX_TRACES * 4)
        self.sampler: dict | None = None
        self.last_ok_t = 0.0
        self.failures = 0
        self.stale = True  # never pulled yet
        self.pulls = 0


class FleetAggregator:
    """Merge N replicas' telemetry into one fleet view (module docstring).

    Sources are callables `pull(since_seq) -> payload` (build_telemetry
    shape). Thread-safe: pull_all serializes rounds; readers snapshot
    under the same lock."""

    def __init__(self, stale_after_s: float = 15.0, clock=time.monotonic) -> None:
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: dict[str, _SourceState] = {}
        self.rounds = 0

    # ------------------------------------------------------------- sources
    def add_source(self, name: str, pull: Callable[[int], dict]) -> None:
        with self._lock:
            self._sources[name] = _SourceState(pull)

    def add_replica_client(self, name: str, client: Any) -> None:
        """Source over a remote ReplicaClient (sched/replica.py
        telemetry_pull wire op)."""
        self.add_source(
            name, lambda since, c=client: c.telemetry_pull(since_seq=since)
        )

    def add_local(
        self, name: str, stats_provider: Callable[[], dict],
        recorder: Any = None, sampler: Any = None,
    ) -> None:
        """In-process source (FleetReplica / bench harnesses)."""
        self.add_source(
            name,
            lambda since, sp=stats_provider, r=recorder, s=sampler:
                build_telemetry(sp(), r, s, since_seq=since),
        )

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    # --------------------------------------------------------------- pull
    def pull_all(self) -> dict[str, Any]:
        """One aggregation round: pull every source from its cursor.

        A raising source is marked failed (stale once stale_after_s has
        passed since its last good pull) and the round continues over the
        survivors — a dead replica degrades the view, never blocks it. A
        truncated slice advances the cursor and catches up next round."""
        with self._lock:
            sources = list(self._sources.items())
        ok = failed = 0
        for name, st in sources:
            try:
                payload = st.pull(st.cursor)
            except Exception as exc:
                st.failures += 1
                failed += 1
                logger.warning(
                    "telemetry pull from %s failed (%d consecutive): %s",
                    name, st.failures, exc,
                )
                continue
            with self._lock:
                st.failures = 0
                st.pulls += 1
                st.last_ok_t = self._clock()
                st.stale = False
                st.stats = payload.get("stats") or {}
                for entry in payload.get("traces") or []:
                    st.traces.append(entry)
                st.cursor = int(payload.get("next_cursor", st.cursor))
                if payload.get("sampler") is not None:
                    st.sampler = payload["sampler"]
            ok += 1
        with self._lock:
            self.rounds += 1
            now = self._clock()
            for _, st in sources:
                if st.failures and now - st.last_ok_t > self.stale_after_s:
                    st.stale = True
        return {"ok": ok, "failed": failed, "sources": len(sources)}

    # ------------------------------------------------------------- merged
    def merged_stats(self) -> dict[str, Any]:
        """One fleet-wide stats tree: counters summed, histograms merged
        bucket-by-bucket, percentiles recomputed from the merged buckets.
        Stale members still contribute their last-known payload (marked
        in source_status — known-stale data beats a silent hole)."""
        with self._lock:
            trees = [st.stats for st in self._sources.values() if st.stats]
        return _merge_stats(trees)

    def fleet_percentiles(self, phase: str = "decide") -> dict | None:
        """Fleet p50/p95/p99 of one phase from the MERGED buckets."""
        merged = self.merged_stats()
        entry = (merged.get("phases") or {}).get(phase)
        if not entry:
            return None
        return {
            k: entry[k]
            for k in ("count", "p50_ms", "p95_ms", "p99_ms", "max_ms")
        }

    def traces(self, n: int = 100) -> list[dict]:
        """Newest-last merged trace list, STITCHED by trace id: slices of
        the same trace pulled from different replicas (coordinator +
        worker sides of one decision) fuse into one entry with the union
        of their spans."""
        with self._lock:
            rows: list[tuple[str, dict]] = [
                (name, entry)
                for name, st in self._sources.items()
                for entry in st.traces
            ]
        by_id: dict[str, dict] = {}
        order: list[str] = []
        for source, entry in rows:
            tid = entry.get("trace_id")
            if tid not in by_id:
                merged = dict(entry)
                merged["spans"] = list(entry.get("spans") or [])
                merged["sources"] = [source]
                by_id[tid] = merged
                order.append(tid)
                continue
            tgt = by_id[tid]
            have = {s.get("span_id") for s in tgt["spans"]}
            tgt["spans"].extend(
                s for s in entry.get("spans") or []
                if s.get("span_id") not in have
            )
            meta = dict(tgt.get("meta") or {})
            meta.update(entry.get("meta") or {})
            tgt["meta"] = meta
            if source not in tgt["sources"]:
                tgt["sources"].append(source)
            # root-side fields win (the earlier-starting entry is the root)
            if (entry.get("start_unix") or 0) < (tgt.get("start_unix") or 0):
                for field in ("name", "start_unix", "dur_ms", "status"):
                    if field in entry:
                        tgt[field] = entry[field]
        merged_list = [by_id[tid] for tid in order]
        merged_list.sort(key=lambda e: e.get("start_unix") or 0.0)
        return merged_list[-n:]

    def source_status(self) -> dict[str, dict]:
        with self._lock:
            now = self._clock()
            return {
                name: {
                    "stale": st.stale,
                    "failures": st.failures,
                    "pulls": st.pulls,
                    "cursor": st.cursor,
                    "age_s": (
                        round(now - st.last_ok_t, 1) if st.last_ok_t else None
                    ),
                    "traces_held": len(st.traces),
                }
                for name, st in self._sources.items()
            }

    def render_prometheus(self) -> str:
        """ONE merged exposition for the whole fleet (same renderer the
        per-replica /metrics uses, over the merged tree)."""
        from k8s_llm_scheduler_tpu.observability.metrics import (
            render_prometheus,
        )

        return render_prometheus(self.merged_stats())

    def snapshot(self) -> dict[str, Any]:
        return {
            "rounds": self.rounds,
            "sources": self.source_status(),
            "merged": self.merged_stats(),
        }


def render_top(agg: FleetAggregator, phases=("decide", "bind")) -> str:
    """The `cli fleet top` frame: fleet percentiles from merged buckets +
    a per-source row (decisions, decide p99, staleness)."""
    lines: list[str] = []
    merged = agg.merged_stats()
    status = agg.source_status()
    live = sum(1 for s in status.values() if not s["stale"])
    lines.append(
        f"fleet telemetry — {live}/{len(status)} sources live, "
        f"{agg.rounds} rounds"
    )
    for phase in phases:
        pct = (merged.get("phases") or {}).get(phase)
        if pct:
            lines.append(
                f"  fleet {phase:<8} n={pct['count']:<8} "
                f"p50={pct['p50_ms']:.1f}ms p95={pct['p95_ms']:.1f}ms "
                f"p99={pct['p99_ms']:.1f}ms max={pct['max_ms']:.1f}ms"
            )
    totals = {
        key: merged.get(key, 0)
        for key in (
            "total_scheduled", "llm_decisions", "cache_decisions",
            "fallback_decisions", "failed_bindings",
        )
    }
    lines.append(
        "  totals   "
        + "  ".join(f"{k}={v}" for k, v in totals.items())
    )
    # Fleet resident-loop headline (merge sums per-replica tok/s — the
    # fleet figure is genuine aggregate throughput, not an average).
    pers = merged.get("persistent")
    if pers:
        lines.append(
            "  resident "
            f"tok/s={float(pers.get('resident_tokens_per_s', 0.0)):.1f}  "
            f"tokens_total={int(pers.get('tokens_total', 0))}  "
            f"loop_windows={int(pers.get('loop_windows', 0))}"
        )
    with agg._lock:
        per_source = {
            name: st.stats for name, st in agg._sources.items()
        }
    lines.append(
        f"  {'source':<14} {'bound':>7} {'llm':>6} {'cache':>6} "
        f"{'decide_p99':>11} {'ring':>5} {'res_tok/s':>10} "
        f"{'shards':<18} state"
    )
    for name, stats in sorted(per_source.items()):
        st = status[name]
        phases_d = (stats.get("phases") or {}).get("decide") or {}
        shards = stats.get("owned_shards")
        pool = stats.get("pool_role")
        tag = f"pool={pool}" if pool else ""
        # Resident-loop columns: token-ring occupancy from the flat
        # persistent server counters (nested under "engine" by
        # sched/client.get_stats), resident tok/s from the hoisted
        # profiler gauge family. "-" when the replica has no resident
        # loop — most fleets are mixed during a persistent rollout.
        eng = stats.get("engine") or {}
        occ = eng.get("persistent_ring_occupancy_frac")
        ring = f"{occ:.2f}" if isinstance(occ, (int, float)) else "-"
        pers = stats.get("persistent") or eng.get("persistent") or {}
        tps = pers.get("resident_tokens_per_s")
        res = f"{tps:.1f}" if isinstance(tps, (int, float)) else "-"
        lines.append(
            f"  {name:<14} {stats.get('total_scheduled', 0):>7} "
            f"{stats.get('llm_decisions', 0):>6} "
            f"{stats.get('cache_decisions', 0):>6} "
            f"{phases_d.get('p99_ms', 0.0):>9.1f}ms "
            f"{ring:>5} {res:>10} "
            f"{str(shards if shards is not None else '-'):<18} "
            + ("STALE" if st["stale"] else "live")
            + (f" {tag}" if tag else "")
        )
    return "\n".join(lines)
