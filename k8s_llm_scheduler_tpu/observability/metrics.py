"""Prometheus-style metrics endpoint + debug surfaces.

The reference *declares* `metrics: {enabled, port: 9090}` in its config but
no server exists — the keys are read by nothing (reference config.yaml:29-31,
SURVEY §5 "dead config"; README.md:184 defers it to future work). This module
makes the endpoint real: a stdlib ThreadingHTTPServer serving

    /metrics           Prometheus text exposition of scheduler + engine stats
                       (gauges, plus genuine `histogram` families for every
                       PhaseRecorder phase — `_bucket`/`_sum`/`_count` with
                       derived p50/p95/p99 gauges beside them)
    /healthz           liveness (200 when the loop is running)
    /stats             the full merged stats dict as JSON
    /debug/decisions   flight-recorder trace summaries (observability/spans;
                       ?n= limit, ?since= seq cursor for `cli trace tail`)
    /debug/trace/<id>  one complete decision trace (span tree + metadata)
    /debug/export      every held trace as JSONL (replayable records)
    /debug/engine      engine telemetry ring series (observability/sampler)

Stats are pulled from a provider callable at scrape time — no push path,
no extra locks on the hot path.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from k8s_llm_scheduler_tpu.observability.trace import BUCKET_BOUNDS_S, HIST_KEY

logger = logging.getLogger(__name__)

_PREFIX = "llm_scheduler"


def _escape_label_value(value: str) -> str:
    """Escape a label VALUE per the Prometheus exposition spec: backslash,
    double quote, and newline must be escaped or the line is unparseable
    (a node name or breaker-state string containing any of them previously
    emitted invalid exposition text)."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _flatten(stats: dict[str, Any], prefix: str = "") -> dict[str, float]:
    out: dict[str, float] = {}
    for key, value in stats.items():
        if key == HIST_KEY:
            continue  # histogram payloads render as their own families
        name = f"{prefix}_{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_flatten(value, name))
        elif isinstance(value, bool):
            out[name] = 1.0 if value else 0.0
        elif isinstance(value, (int, float)):
            out[name] = float(value)
        # strings (e.g. breaker state) become labeled gauges below
        elif isinstance(value, str):
            out[f'{name}{{value="{_escape_label_value(value)}"}}'] = 1.0
        elif isinstance(value, (list, tuple)):
            # index-labeled gauges: per-replica lists (fanout_routed) and
            # per-wave arena series (sim/arena) were silently DROPPED
            # before this — a scrape showed totals but never the series
            for i, item in enumerate(value):
                if isinstance(item, dict):
                    out.update(_flatten(item, f"{name}_{i}"))
                elif isinstance(item, bool):
                    out[f'{name}{{index="{i}"}}'] = 1.0 if item else 0.0
                elif isinstance(item, (int, float)):
                    out[f'{name}{{index="{i}"}}'] = float(item)
    return out


def _collect_histograms(
    stats: dict[str, Any], prefix: str = ""
) -> list[tuple[str, dict]]:
    """(flattened path, histogram payload) pairs for every embedded
    PhaseRecorder histogram (trace.HIST_KEY dicts) in the stats tree."""
    out: list[tuple[str, dict]] = []
    for key, value in stats.items():
        if not isinstance(value, dict):
            continue
        name = f"{prefix}_{key}" if prefix else key
        hist = value.get(HIST_KEY)
        if (
            isinstance(hist, dict)
            and "counts" in hist
            and len(hist["counts"]) == len(BUCKET_BOUNDS_S) + 1
        ):
            out.append((name, hist))
        out.extend(_collect_histograms(value, name))
    return out


def _format_bound(bound: float) -> str:
    """Stable short text for a bucket bound (no float noise in labels)."""
    text = f"{bound:.10f}".rstrip("0").rstrip(".")
    return text or "0"


def render_prometheus(stats: dict[str, Any]) -> str:
    # Group samples by metric FAMILY (name sans labels) so each family gets
    # exactly one `# TYPE <family> gauge` header with its samples contiguous
    # under it — the exposition-format contract scrapers validate (bare
    # samples with no TYPE parse, but registries flag them and typed
    # queries treat them as untyped). Point-in-time readings render as
    # gauges; PhaseRecorder phases additionally render as genuine
    # `histogram` families (cumulative `_bucket{le=...}` + `_sum`/`_count`)
    # so bind p99 under burst is a PromQL histogram_quantile away, not a
    # guess from an average.
    families: dict[str, list[tuple[str, float]]] = {}
    for name, value in sorted(_flatten(stats).items()):
        metric = f"{_PREFIX}_{name}"
        family = metric
        # metric names cannot contain '{' — split label part back out
        if "{" in name:
            base, label = name.split("{", 1)
            family = f"{_PREFIX}_{base}"
            metric = f"{family}{{{label}"
        families.setdefault(family, []).append((metric, value))
    lines = []
    for family, samples in families.items():
        lines.append(f"# TYPE {family} gauge")
        lines.extend(f"{metric} {value}" for metric, value in samples)
    for path, hist in sorted(_collect_histograms(stats)):
        family = f"{_PREFIX}_{path}_seconds"
        lines.append(f"# TYPE {family} histogram")
        acc = 0
        for bound, count in zip(BUCKET_BOUNDS_S, hist["counts"]):
            acc += int(count)
            lines.append(
                f'{family}_bucket{{le="{_format_bound(bound)}"}} {acc}'
            )
        acc += int(hist["counts"][-1])  # overflow bucket
        lines.append(f'{family}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{family}_sum {float(hist['sum_s'])}")
        lines.append(f"{family}_count {int(hist['count'])}")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Serve scheduler stats on the (formerly dead) metrics port.

    `flight_recorder` (default: the global spans.flight) backs the
    /debug/decisions + /debug/trace surfaces; `engine_sampler` (optional)
    backs /debug/engine."""

    def __init__(
        self,
        stats_provider: Callable[[], dict[str, Any]],
        port: int = 9090,
        host: str = "0.0.0.0",
        is_alive: Callable[[], bool] = lambda: True,
        flight_recorder: Any | None = None,
        engine_sampler: Any | None = None,
    ) -> None:
        from k8s_llm_scheduler_tpu.observability import spans

        self.stats_provider = stats_provider
        self.is_alive = is_alive
        self.flight_recorder = (
            flight_recorder if flight_recorder is not None else spans.flight
        )
        self.engine_sampler = engine_sampler

        server = self

        class Handler(BaseHTTPRequestHandler):
            # Socket deadline for the whole exchange: a stalled scraper
            # (connects, never finishes its request, or stops reading the
            # response) must not pin a handler thread forever.
            timeout = 10.0

            def do_GET(self) -> None:  # noqa: N802
                try:
                    body, ctype, code = server._route(self.path)
                except Exception as exc:  # pragma: no cover
                    body, ctype, code = str(exc).encode(), "text/plain", 500
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError, TimeoutError):
                    # Client disconnected mid-write (or stopped reading past
                    # the socket timeout): nothing to deliver to, and a
                    # traceback from the handler thread helps nobody.
                    self.close_connection = True

            def handle(self) -> None:
                # BaseHTTPRequestHandler surfaces a socket timeout (the
                # class attr above) by raising from rfile reads; contain it
                # like a disconnect instead of dumping a thread traceback.
                try:
                    super().handle()
                except (
                    BrokenPipeError, ConnectionResetError, TimeoutError
                ):
                    self.close_connection = True

            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("metrics: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved (port=0 ok)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics"
        )

    # ------------------------------------------------------------- routing
    @staticmethod
    def _query_int(path: str, key: str, default: int) -> int:
        from urllib.parse import parse_qs, urlsplit

        try:
            values = parse_qs(urlsplit(path).query).get(key)
            return int(values[0]) if values else default
        except (ValueError, TypeError):
            return default

    def _route(self, path: str) -> tuple[bytes, str, int]:
        if path.startswith("/metrics"):
            return (
                render_prometheus(self.stats_provider()).encode(),
                "text/plain; version=0.0.4",
                200,
            )
        if path.startswith("/healthz"):
            ok = self.is_alive()
            return (b"ok" if ok else b"not running"), "text/plain", (
                200 if ok else 503
            )
        if path.startswith("/stats"):
            return (
                json.dumps(self.stats_provider()).encode(),
                "application/json",
                200,
            )
        if path.startswith("/debug/decisions"):
            body = json.dumps({
                "recorder": self.flight_recorder.stats(),
                "traces": self.flight_recorder.list(
                    n=self._query_int(path, "n", 50),
                    since_seq=self._query_int(path, "since", 0),
                ),
            }).encode()
            return body, "application/json", 200
        if path.startswith("/debug/trace/"):
            from urllib.parse import urlsplit

            trace_id = urlsplit(path).path[len("/debug/trace/"):]
            entry = self.flight_recorder.get(trace_id)
            if entry is None:
                return b"trace not found (ring may have evicted it)", (
                    "text/plain"
                ), 404
            return json.dumps(entry).encode(), "application/json", 200
        if path.startswith("/debug/export"):
            return (
                self.flight_recorder.export_jsonl().encode(),
                "application/x-ndjson",
                200,
            )
        if path.startswith("/debug/engine"):
            if self.engine_sampler is None:
                return b"no engine sampler attached", "text/plain", 404
            if self.engine_sampler.samples_taken == 0:
                # cold sampler (queried before its first interval): tick
                # it once so the endpoint answers with data, not an empty
                # ring — sample_once is read-only against the engine
                try:
                    self.engine_sampler.sample_once()
                except Exception:
                    logger.exception("cold engine sample failed")
            return (
                json.dumps(self.engine_sampler.series()).encode(),
                "application/json",
                200,
            )
        return b"not found", "text/plain", 404

    def start(self) -> None:
        self._thread.start()
        logger.info(
            "metrics endpoint on :%d (/metrics /healthz /stats /debug/*)",
            self.port,
        )

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
