"""Prometheus-style metrics endpoint.

The reference *declares* `metrics: {enabled, port: 9090}` in its config but
no server exists — the keys are read by nothing (reference config.yaml:29-31,
SURVEY §5 "dead config"; README.md:184 defers it to future work). This module
makes the endpoint real: a stdlib ThreadingHTTPServer serving

    /metrics   Prometheus text exposition of the scheduler + engine stats
    /healthz   liveness (200 when the loop is running)
    /stats     the full merged stats dict as JSON

Stats are pulled from a provider callable at scrape time — no push path,
no extra locks on the hot path.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

logger = logging.getLogger(__name__)

_PREFIX = "llm_scheduler"


def _flatten(stats: dict[str, Any], prefix: str = "") -> dict[str, float]:
    out: dict[str, float] = {}
    for key, value in stats.items():
        name = f"{prefix}_{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_flatten(value, name))
        elif isinstance(value, bool):
            out[name] = 1.0 if value else 0.0
        elif isinstance(value, (int, float)):
            out[name] = float(value)
        # strings (e.g. breaker state) become labeled gauges below
        elif isinstance(value, str):
            out[f"{name}{{value=\"{value}\"}}"] = 1.0
        elif isinstance(value, (list, tuple)):
            # index-labeled gauges: per-replica lists (fanout_routed) and
            # per-wave arena series (sim/arena) were silently DROPPED
            # before this — a scrape showed totals but never the series
            for i, item in enumerate(value):
                if isinstance(item, dict):
                    out.update(_flatten(item, f"{name}_{i}"))
                elif isinstance(item, bool):
                    out[f"{name}{{index=\"{i}\"}}"] = 1.0 if item else 0.0
                elif isinstance(item, (int, float)):
                    out[f"{name}{{index=\"{i}\"}}"] = float(item)
    return out


def render_prometheus(stats: dict[str, Any]) -> str:
    # Group samples by metric FAMILY (name sans labels) so each family gets
    # exactly one `# TYPE <family> gauge` header with its samples contiguous
    # under it — the exposition-format contract scrapers validate (bare
    # samples with no TYPE parse, but registries flag them and typed
    # queries treat them as untyped). Everything here is a point-in-time
    # reading of a stats dict, so gauge is the honest type for all of it.
    families: dict[str, list[tuple[str, float]]] = {}
    for name, value in sorted(_flatten(stats).items()):
        metric = f"{_PREFIX}_{name}"
        family = metric
        # metric names cannot contain '{' — split label part back out
        if "{" in name:
            base, label = name.split("{", 1)
            family = f"{_PREFIX}_{base}"
            metric = f"{family}{{{label}"
        families.setdefault(family, []).append((metric, value))
    lines = []
    for family, samples in families.items():
        lines.append(f"# TYPE {family} gauge")
        lines.extend(f"{metric} {value}" for metric, value in samples)
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Serve scheduler stats on the (formerly dead) metrics port."""

    def __init__(
        self,
        stats_provider: Callable[[], dict[str, Any]],
        port: int = 9090,
        host: str = "0.0.0.0",
        is_alive: Callable[[], bool] = lambda: True,
    ) -> None:
        self.stats_provider = stats_provider
        self.is_alive = is_alive

        provider = self.stats_provider
        alive = self.is_alive

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802
                try:
                    if self.path.startswith("/metrics"):
                        body = render_prometheus(provider()).encode()
                        ctype = "text/plain; version=0.0.4"
                        code = 200
                    elif self.path.startswith("/healthz"):
                        ok = alive()
                        body = (b"ok" if ok else b"not running")
                        ctype = "text/plain"
                        code = 200 if ok else 503
                    elif self.path.startswith("/stats"):
                        body = json.dumps(provider()).encode()
                        ctype = "application/json"
                        code = 200
                    else:
                        body, ctype, code = b"not found", "text/plain", 404
                except Exception as exc:  # pragma: no cover
                    body, ctype, code = str(exc).encode(), "text/plain", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("metrics: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved (port=0 ok)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics"
        )

    def start(self) -> None:
        self._thread.start()
        logger.info("metrics endpoint on :%d (/metrics /healthz /stats)", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
