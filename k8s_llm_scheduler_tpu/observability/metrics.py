"""Prometheus-style metrics endpoint + debug surfaces.

The reference *declares* `metrics: {enabled, port: 9090}` in its config but
no server exists — the keys are read by nothing (reference config.yaml:29-31,
SURVEY §5 "dead config"; README.md:184 defers it to future work). This module
makes the endpoint real: a stdlib ThreadingHTTPServer serving

    /metrics           Prometheus text exposition of scheduler + engine stats
                       (gauges, plus genuine `histogram` families for every
                       PhaseRecorder phase — `_bucket`/`_sum`/`_count` with
                       derived p50/p95/p99 gauges beside them)
    /healthz           liveness (200 when the loop is running)
    /stats             the full merged stats dict as JSON
    /debug/decisions   flight-recorder trace summaries (observability/spans;
                       ?n= limit, ?since= seq cursor for `cli trace tail`,
                       ?max_bytes= hard size cap -> truncated/next_cursor)
    /debug/trace/<id>  one complete decision trace (span tree + metadata)
    /debug/export      held traces as JSONL (replayable records; ?since= +
                       ?max_bytes= paginate — a trailer line carries
                       {"truncated": true, "next_cursor": N} on a capped
                       response so a resume never re-ships the prefix)
    /debug/engine      engine telemetry ring series (observability/sampler)
    /debug/profile     continuous wave profiler: per-wave step timeline,
                       segment fractions, MFU loss decomposition
                       (observability/profiler)
    /debug/slo         SLO burn-rate engine state: per-objective fast/slow
                       burn + trips (observability/slo)

Stats are pulled from a provider callable at scrape time — no push path,
no extra locks on the hot path. When an engine sampler / profiler / SLO
engine is attached, their latest readings merge into the /metrics
exposition as gauges HERE (not in caller wiring), so they are visible to
scrapers regardless of which stats provider the server was built with.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from k8s_llm_scheduler_tpu.observability.trace import BUCKET_BOUNDS_S, HIST_KEY

logger = logging.getLogger(__name__)

_PREFIX = "llm_scheduler"


def _escape_label_value(value: str) -> str:
    """Escape a label VALUE per the Prometheus exposition spec: backslash,
    double quote, and newline must be escaped or the line is unparseable
    (a node name or breaker-state string containing any of them previously
    emitted invalid exposition text)."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _flatten(stats: dict[str, Any], prefix: str = "") -> dict[str, float]:
    out: dict[str, float] = {}
    for key, value in stats.items():
        if key == HIST_KEY:
            continue  # histogram payloads render as their own families
        name = f"{prefix}_{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_flatten(value, name))
        elif isinstance(value, bool):
            out[name] = 1.0 if value else 0.0
        elif isinstance(value, (int, float)):
            out[name] = float(value)
        # strings (e.g. breaker state) become labeled gauges below
        elif isinstance(value, str):
            out[f'{name}{{value="{_escape_label_value(value)}"}}'] = 1.0
        elif isinstance(value, (list, tuple)):
            # index-labeled gauges: per-replica lists (fanout_routed) and
            # per-wave arena series (sim/arena) were silently DROPPED
            # before this — a scrape showed totals but never the series
            for i, item in enumerate(value):
                if isinstance(item, dict):
                    out.update(_flatten(item, f"{name}_{i}"))
                elif isinstance(item, bool):
                    out[f'{name}{{index="{i}"}}'] = 1.0 if item else 0.0
                elif isinstance(item, (int, float)):
                    out[f'{name}{{index="{i}"}}'] = float(item)
    return out


def _collect_histograms(
    stats: dict[str, Any], prefix: str = ""
) -> list[tuple[str, dict]]:
    """(flattened path, histogram payload) pairs for every embedded
    PhaseRecorder histogram (trace.HIST_KEY dicts) in the stats tree."""
    out: list[tuple[str, dict]] = []
    for key, value in stats.items():
        if not isinstance(value, dict):
            continue
        name = f"{prefix}_{key}" if prefix else key
        hist = value.get(HIST_KEY)
        if (
            isinstance(hist, dict)
            and "counts" in hist
            and len(hist["counts"]) == len(BUCKET_BOUNDS_S) + 1
        ):
            out.append((name, hist))
        out.extend(_collect_histograms(value, name))
    return out


def _format_bound(bound: float) -> str:
    """Stable short text for a bucket bound (no float noise in labels)."""
    text = f"{bound:.10f}".rstrip("0").rstrip(".")
    return text or "0"


def render_prometheus(stats: dict[str, Any]) -> str:
    # Group samples by metric FAMILY (name sans labels) so each family gets
    # exactly one `# TYPE <family> gauge` header with its samples contiguous
    # under it — the exposition-format contract scrapers validate (bare
    # samples with no TYPE parse, but registries flag them and typed
    # queries treat them as untyped). Point-in-time readings render as
    # gauges; PhaseRecorder phases additionally render as genuine
    # `histogram` families (cumulative `_bucket{le=...}` + `_sum`/`_count`)
    # so bind p99 under burst is a PromQL histogram_quantile away, not a
    # guess from an average.
    families: dict[str, list[tuple[str, float]]] = {}
    for name, value in sorted(_flatten(stats).items()):
        metric = f"{_PREFIX}_{name}"
        family = metric
        # metric names cannot contain '{' — split label part back out
        if "{" in name:
            base, label = name.split("{", 1)
            family = f"{_PREFIX}_{base}"
            metric = f"{family}{{{label}"
        families.setdefault(family, []).append((metric, value))
    lines = []
    for family, samples in families.items():
        lines.append(f"# TYPE {family} gauge")
        lines.extend(f"{metric} {value}" for metric, value in samples)
    for path, hist in sorted(_collect_histograms(stats)):
        family = f"{_PREFIX}_{path}_seconds"
        lines.append(f"# TYPE {family} histogram")
        acc = 0
        for bound, count in zip(BUCKET_BOUNDS_S, hist["counts"]):
            acc += int(count)
            lines.append(
                f'{family}_bucket{{le="{_format_bound(bound)}"}} {acc}'
            )
        acc += int(hist["counts"][-1])  # overflow bucket
        lines.append(f'{family}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{family}_sum {float(hist['sum_s'])}")
        lines.append(f"{family}_count {int(hist['count'])}")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Serve scheduler stats on the (formerly dead) metrics port.

    `flight_recorder` (default: the global spans.flight) backs the
    /debug/decisions + /debug/trace surfaces; `engine_sampler` (optional)
    backs /debug/engine; `engine_profiler` (optional) backs /debug/profile;
    `slo_engine` (optional) backs /debug/slo. All three also contribute
    gauges to /metrics at scrape time."""

    # Default hard byte caps on the paginated debug surfaces: a
    # 16-replica telemetry_pull round must never ship unbounded JSONL in
    # one frame (?max_bytes= overrides per request).
    DECISIONS_MAX_BYTES = 1 << 20
    EXPORT_MAX_BYTES = 4 << 20

    def __init__(
        self,
        stats_provider: Callable[[], dict[str, Any]],
        port: int = 9090,
        host: str = "0.0.0.0",
        is_alive: Callable[[], bool] = lambda: True,
        flight_recorder: Any | None = None,
        engine_sampler: Any | None = None,
        engine_profiler: Any | None = None,
        slo_engine: Any | None = None,
        blackbox_provider: Callable[[], dict[str, Any] | None] | None = None,
    ) -> None:
        from k8s_llm_scheduler_tpu.observability import spans

        self.stats_provider = stats_provider
        self.is_alive = is_alive
        self.flight_recorder = (
            flight_recorder if flight_recorder is not None else spans.flight
        )
        self.engine_sampler = engine_sampler
        self.engine_profiler = engine_profiler
        self.slo_engine = slo_engine
        # /debug/blackbox: the persistent loop's wedge black-box dump
        # (engine.persistent_blackbox) — None/absent when the backend has
        # no resident loop or telemetry is off.
        self.blackbox_provider = blackbox_provider

        server = self

        class Handler(BaseHTTPRequestHandler):
            # Socket deadline for the whole exchange: a stalled scraper
            # (connects, never finishes its request, or stops reading the
            # response) must not pin a handler thread forever.
            timeout = 10.0

            def do_GET(self) -> None:  # noqa: N802
                try:
                    body, ctype, code = server._route(self.path)
                except Exception as exc:  # pragma: no cover
                    body, ctype, code = str(exc).encode(), "text/plain", 500
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError, TimeoutError):
                    # Client disconnected mid-write (or stopped reading past
                    # the socket timeout): nothing to deliver to, and a
                    # traceback from the handler thread helps nobody.
                    self.close_connection = True

            def handle(self) -> None:
                # BaseHTTPRequestHandler surfaces a socket timeout (the
                # class attr above) by raising from rfile reads; contain it
                # like a disconnect instead of dumping a thread traceback.
                try:
                    super().handle()
                except (
                    BrokenPipeError, ConnectionResetError, TimeoutError
                ):
                    self.close_connection = True

            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("metrics: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved (port=0 ok)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics"
        )

    # ------------------------------------------------------------- routing
    @staticmethod
    def _query_int(path: str, key: str, default: int) -> int:
        from urllib.parse import parse_qs, urlsplit

        try:
            values = parse_qs(urlsplit(path).query).get(key)
            return int(values[0]) if values else default
        except (ValueError, TypeError):
            return default

    def _scrape_stats(self) -> dict[str, Any]:
        """Provider stats + attached-component gauges. The merge lives in
        the server (not caller wiring) so EngineSampler ring series /
        profiler segments / SLO burns are real Prometheus gauges whenever
        the component is attached — previously the sampler was visible to
        scrapers only when one specific CLI path wrapped the provider."""
        stats = dict(self.stats_provider())
        if self.engine_sampler is not None:
            stats["engine_telemetry"] = self.engine_sampler.latest()
        if self.engine_profiler is not None:
            stats["engine_profile"] = self.engine_profiler.gauges()
            # Mount the llm_scheduler_persistent_* family at the top
            # level (not under engine_profile) so the gauge names match
            # across /metrics, the SLO provider tree, and the fleet
            # merge; never clobber a provider-supplied subtree.
            if "persistent" not in stats and hasattr(
                self.engine_profiler, "persistent_gauges"
            ):
                stats["persistent"] = self.engine_profiler.persistent_gauges()
        if self.slo_engine is not None:
            stats["slo"] = self.slo_engine.gauges()
        return stats

    def _route(self, path: str) -> tuple[bytes, str, int]:
        if path.startswith("/metrics"):
            return (
                render_prometheus(self._scrape_stats()).encode(),
                "text/plain; version=0.0.4",
                200,
            )
        if path.startswith("/healthz"):
            ok = self.is_alive()
            return (b"ok" if ok else b"not running"), "text/plain", (
                200 if ok else 503
            )
        if path.startswith("/stats"):
            return (
                json.dumps(self.stats_provider()).encode(),
                "application/json",
                200,
            )
        if path.startswith("/debug/decisions"):
            from k8s_llm_scheduler_tpu.observability.spans import (
                budget_slice,
            )

            n = self._query_int(path, "n", 50)
            since = self._query_int(path, "since", -1)
            max_bytes = self._query_int(
                path, "max_bytes", self.DECISIONS_MAX_BYTES
            )
            if since >= 0:
                # Forward-pagination walk (`cli trace tail`, resume after
                # a truncated response): oldest-first past the cursor,
                # with BOTH the n cut and the byte cap surfacing as
                # truncated/next_cursor — a newest-n cut here would skip
                # older entries without the client ever knowing.
                summaries = self.flight_recorder.list(
                    n=None, since_seq=since,
                )
                kept, next_cursor, truncated = budget_slice(
                    summaries, since_seq=since,
                    max_traces=n, max_bytes=max_bytes,
                )
            else:
                # No cursor: the recent-traces view (`cli trace list`) —
                # newest n, byte cap keeping the oldest of that window so
                # a resume via next_cursor still walks forward.
                summaries = self.flight_recorder.list(n=n)
                kept, next_cursor, truncated = budget_slice(
                    summaries, max_bytes=max_bytes,
                )
            body = json.dumps({
                "recorder": self.flight_recorder.stats(),
                "traces": kept,
                "truncated": truncated,
                "next_cursor": next_cursor,
            }).encode()
            return body, "application/json", 200
        if path.startswith("/debug/trace/"):
            from urllib.parse import urlsplit

            trace_id = urlsplit(path).path[len("/debug/trace/"):]
            entry = self.flight_recorder.get(trace_id)
            if entry is None:
                return b"trace not found (ring may have evicted it)", (
                    "text/plain"
                ), 404
            return json.dumps(entry).encode(), "application/json", 200
        if path.startswith("/debug/export"):
            since = self._query_int(path, "since", 0)
            entries, next_cursor, truncated = (
                self.flight_recorder.export_slices(
                    since_seq=since,
                    max_bytes=self._query_int(
                        path, "max_bytes", self.EXPORT_MAX_BYTES
                    ),
                )
            )
            lines = [
                json.dumps(e, sort_keys=True, separators=(",", ":"))
                for e in entries
            ]
            if truncated:
                # trailer line, still valid JSONL: consumers resume from
                # next_cursor without re-shipping the prefix
                lines.append(json.dumps(
                    {"truncated": True, "next_cursor": next_cursor},
                    sort_keys=True, separators=(",", ":"),
                ))
            body = ("".join(line + "\n" for line in lines)).encode()
            return body, "application/x-ndjson", 200
        if path.startswith("/debug/engine"):
            if self.engine_sampler is None:
                return b"no engine sampler attached", "text/plain", 404
            if self.engine_sampler.samples_taken == 0:
                # cold sampler (queried before its first interval): tick
                # it once so the endpoint answers with data, not an empty
                # ring — sample_once is read-only against the engine
                try:
                    self.engine_sampler.sample_once()
                except Exception:
                    logger.exception("cold engine sample failed")
            return (
                json.dumps(self.engine_sampler.series()).encode(),
                "application/json",
                200,
            )
        if path.startswith("/debug/profile"):
            if self.engine_profiler is None:
                return b"no engine profiler attached", "text/plain", 404
            return (
                json.dumps(self.engine_profiler.snapshot()).encode(),
                "application/json",
                200,
            )
        if path.startswith("/debug/slo"):
            if self.slo_engine is None:
                return b"no slo engine attached", "text/plain", 404
            return (
                json.dumps(self.slo_engine.snapshot()).encode(),
                "application/json",
                200,
            )
        if path.startswith("/debug/blackbox"):
            if self.blackbox_provider is None:
                return b"no persistent black-box attached", "text/plain", 404
            dump = self.blackbox_provider()
            if dump is None:
                return (
                    b"no black-box dump yet (no residency, or telemetry "
                    b"off)",
                    "text/plain",
                    404,
                )
            return json.dumps(dump).encode(), "application/json", 200
        return b"not found", "text/plain", 404

    def start(self) -> None:
        self._thread.start()
        logger.info(
            "metrics endpoint on :%d (/metrics /healthz /stats /debug/*)",
            self.port,
        )

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # Attached background components stop WITH the server (idempotent
        # — callers that own them may stop them again): `cli run` exits
        # and tests previously leaked the sampler's daemon thread when a
        # teardown path missed its own stop call.
        if self.engine_sampler is not None:
            self.engine_sampler.stop()
        if self.slo_engine is not None and hasattr(self.slo_engine, "stop"):
            self.slo_engine.stop()
