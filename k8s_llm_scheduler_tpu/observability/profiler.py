"""Continuous engine profiler: per-wave step timelines + MFU loss terms.

Decode MFU at 1B measures 0.072 and the bench attributes 114 ms of p50 to
`dispatch_rtt_ms` — but until this module nothing in the repo could say
WHAT FRACTION of a decode wave's wall time is dispatch-boundary sync
versus host round-trip versus genuine matmul. That is the
synchronization-boundary accounting *Kernel Looping* (PAPERS.md) argues
dominates decode, and it is an attribution problem before it is an
optimization problem: ROADMAP items 1-2 (fused decode loop, dispatch-RTT
kill) need a measurement substrate that names the losses they exist to
remove.

This profiler fences every decision wave with perf_counter reads at each
jax.jit dispatch and block_until_ready boundary (engine/engine.py
submit_wave / harvest_wave; engine/local.py contributes the queue-side
fences) and buckets the wave's wall time into NAMED SEGMENTS that
telescope exactly:

    queue_stall    oldest item enqueued -> submit entered (admission wait,
                   coalescing window, group-switch fairness holds)
    dispatch       submit entered -> jit program enqueued + D2H started
                   (host-side tracing/enqueue cost — the dispatch boundary)
    dispatch_gap   dispatch done -> harvest entered (pipelining overlap:
                   the worker polls the queue / feeds later waves here)
    host_sync      harvest entered -> device_get returned (the
                   block_until_ready boundary: device tail + transfer +
                   host round trip)
    harvest        device_get returned -> results decoded on host
    unattributed   wall - sum(above): clock-fence residue, reported as its
                   own segment so coverage is verifiable (>= 95% of wave
                   wall by construction; the acceptance test pins it)

Overlapping those host segments, `device_compute` estimates when the
device was actually busy on this wave (dispatch end -> result ready; the
ready edge comes from the worker's is_ready() poll, or the device_get
return on a blocking harvest). From token counts and the model config the
profiler computes per-wave achieved FLOPs, so `mfu_decode` decomposes:

    mfu_decode + sum(mfu_loss[segment]) ~= mfu_device

where mfu_device is what the device-busy time alone would achieve and
each loss term charges a named idle segment its share of the gap. The
bounded ring exports at /debug/profile (observability/metrics.py) and the
windowed means surface as Prometheus gauges — this is the layer every
subsequent perf PR proves itself against.

The admission plane (engine/admission/) gets the same treatment: each
packed admission contributes a record whose PACK_SEGMENTS telescope to
its host wall (admission_pack / chunk_prefill / decode_piggyback /
unattributed, sum == wall), and a `prefill_tokens_per_decision` gauge —
windowed (wave suffix + packed + prefix tokens ACTUALLY prefilled) per
decision — measures the delta-encoding claim directly: prefill cost
scaling with what changed, not cluster size. The speculative pipeline
(spec/decoder.py) books per-request SPEC_SEGMENTS (draft / verify /
rollback / unattributed, sum == wall) plus the measured round-overlap
fraction — the draft-runs-in-the-shadow-of-the-verify claim, measured.

Cost discipline: all fencing is perf_counter reads on the PER-WAVE path
(waves run at ~10-60/s, never per token); with no profiler attached the
engine pays one None check per wave. bench.py --preset obs-overhead
re-measures the budget with the profiler on.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any

logger = logging.getLogger(__name__)

# Telescoping host-side segments, in timeline order. `device_compute` is
# NOT in this list: it overlaps dispatch_gap/host_sync and is reported as
# its own (estimated) figure beside them.
SEGMENTS = (
    "queue_stall",
    "dispatch",
    "dispatch_gap",
    "host_sync",
    "harvest",
    "unattributed",
)

# Packed-admission segments (engine.admit_packed — the admission plane),
# telescoping over each pack's host wall time with the same sum==wall
# identity the wave segments keep: admission_pack is host-side packing /
# bookkeeping, chunk_prefill the packed block-diagonal prefill dispatches,
# decode_piggyback the SARATHI decode chunks interleaved between them.
PACK_SEGMENTS = (
    "admission_pack",
    "chunk_prefill",
    "decode_piggyback",
    "unattributed",
)

# Fused-decode segments (engine.step_fused / decode_fused — the fused
# on-device runtime, engine/fused/): telescoping over each fused harvest's
# host wall with the same sum==wall identity. dispatch is the back-to-back
# chunk enqueues (no syncs), host_sync the per-chunk device_get window,
# harvest the host-side token decode after the last sync.
FUSED_SEGMENTS = (
    "dispatch",
    "host_sync",
    "harvest",
    "unattributed",
)

# Persistent-loop segments (engine/persistent/ — the device-resident
# serving loop): telescoping over each step_persistent harvest's host
# wall with the same sum==wall identity. ring_wait is the host blocking
# on TokenRing.drain (waiting for the loop to push), harvest the host-
# side booking of drained batches, loop_resident everything else — the
# window where the device loop ran with NO host involvement at all. The
# whole point of the subsystem is that loop_resident dominates while
# dispatches_per_decision (the flow books below) reads zero.
PERSISTENT_SEGMENTS = (
    "ring_wait",
    "loop_resident",
    "harvest",
    "unattributed",
)

# Sub-decomposition of `loop_resident` itself (observability/resident.py
# + engine._decompose_loop_resident): the device-resident counter block
# exported through the StatsRing splits the opaque in-loop window by
# counter deltas — admissions taken, decode steps run, token-ring
# backpressure stalls, idle chunks. Telescoping over loop_resident with
# the same sum==wall identity (the last segment is the exact remainder),
# pinned like every other segment family. Only booked for windows where
# a telemetry snapshot landed (telemetry off -> sub-books untouched).
PERSISTENT_LOOP_SEGMENTS = (
    "admit",
    "decode",
    "ring_stall",
    "idle",
)

# Speculative-decoding segments (spec/decoder.py — the async
# propose/verify pipeline): telescoping over each spec REQUEST's host
# wall with the same sum==wall identity. draft covers propose dispatches
# (draft prefill + fresh + ahead — ~0 for the hidden arm, whose
# proposals ride inside the verify program), verify the verify dispatch
# plus the round's single device_get, rollback the paged-KV truncate +
# host emit bookkeeping. Beside the segments, the books carry the round
# OVERLAP fraction — rounds whose proposal block was device-resident
# before the round began, i.e. the draft work hidden behind the previous
# verify sync — the async pipeline's headline.
SPEC_SEGMENTS = (
    "draft",
    "verify",
    "rollback",
    "unattributed",
)

# Peak dense bf16 TFLOP/s by jax device_kind (public spec sheets). Shared
# with bench.py's MFU figures so the profiler's decomposition and the
# bench headline always normalize against the same peak.
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def matmul_flops_per_token(cfg) -> float:
    """Dense matmul FLOPs for one token's forward pass (2*MACs).
    Formerly bench.py's accounting — moved here so the profiler's MFU
    decomposition and the bench headline share one set of books."""
    d, hd = cfg.d_model, cfg.head_dim
    attn_proj = (
        d * cfg.n_heads * hd + 2 * d * cfg.n_kv_heads * hd
        + cfg.n_heads * hd * d
    )
    mlp = 3 * d * cfg.d_ff
    lm_head = d * cfg.vocab_size
    return 2.0 * (cfg.n_layers * (attn_proj + mlp) + lm_head)


def attn_flops_per_token(cfg, ctx: float) -> float:
    """Attention score+value FLOPs for one token attending to `ctx` keys."""
    return 4.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * ctx


def detect_peak_tflops(override: float | None = None) -> tuple[float | None, str]:
    """(peak bf16 TFLOP/s or None if unknown, device kind)."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # pragma: no cover - no backend at all
        kind = "unknown"
    if override is not None:
        return override, kind
    return PEAK_BF16_TFLOPS.get(kind), kind


class EngineProfiler:
    """Per-wave step-timeline recorder for one InferenceEngine.

    The engine owns the fences (it is the only code that knows where its
    dispatch and sync boundaries are); this class owns the bookkeeping:
    in-flight wave state keyed by handle identity, a bounded ring of
    completed wave records, and the derived segment/MFU aggregates.

    Thread model: on_submit/note_ready/on_harvest run on the engine-owner
    thread; note_admission runs there too (engine/local._submit_waves).
    snapshot()/gauges() are called from metrics-server handler threads —
    ring and totals are guarded by one lock, acquired once per wave and
    once per scrape.
    """

    def __init__(
        self,
        cfg: Any = None,
        *,
        window: int = 256,
        peak_tflops: float | None = None,
        clock=time.perf_counter,
    ) -> None:
        self.cfg = cfg
        self.window = max(1, int(window))
        self._clock = clock
        peak, kind = detect_peak_tflops(peak_tflops)
        self.peak_flops = peak * 1e12 if peak else None
        self.device_kind = kind
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.window)
        # in-flight fence state, keyed by id(handle): a handle is submitted
        # and harvested exactly once, and the engine-owner thread does both
        self._open: dict[int, dict] = {}
        self._wave_counter = 0
        self._totals = {name: 0.0 for name in SEGMENTS}
        self._totals["device_compute"] = 0.0
        self._totals["wall"] = 0.0
        self._flops_total = 0.0
        self._tokens_total = 0
        self.waves_profiled = 0
        # CUMULATIVE (never-windowed) segment books beside the windowed
        # ones: monotone counters the SLO burn-rate engine can window
        # itself (delta against its own baselines) — an error_rate
        # objective over queue_stall_ms_total/wall_ms_total is the
        # admission-pressure objective the autoscaler consumes without a
        # custom stats provider. The windowed `*_frac` gauges cannot
        # serve that role: eviction makes them non-monotone.
        self._cum = {name: 0.0 for name in SEGMENTS}
        self._cum["wall"] = 0.0
        # Admission-plane books: per-pack records (engine.admit_packed)
        # and the prefill-tokens-per-decision gauge inputs. Prefix
        # prefills contribute only their NON-REUSED tokens — the delta
        # path's O(changed) claim is measured on exactly this figure.
        self._pack_ring: deque[dict] = deque(maxlen=self.window)
        self._pack_totals = {name: 0.0 for name in PACK_SEGMENTS}
        self._pack_totals["wall"] = 0.0
        # Fused-decode books (engine/fused/): per-harvest records with
        # telescoping FUSED_SEGMENTS and their own MFU figure — the
        # before/after proof the fused runtime is measured against.
        self._fused_ring: deque[dict] = deque(maxlen=self.window)
        self._fused_totals = {name: 0.0 for name in FUSED_SEGMENTS}
        self._fused_totals["wall"] = 0.0
        self._fused_flops = 0.0
        self._fused_tokens = 0
        self.fused_profiled = 0
        self._prefix_prefills: deque[tuple[int, int]] = deque(
            maxlen=self.window
        )  # (tokens prefilled, prefix length)
        self.packs_profiled = 0
        # Speculative-pipeline books (spec/decoder.py): per-request
        # records with telescoping SPEC_SEGMENTS plus windowed round /
        # overlap counts — the draft/verify overlap fraction is derived
        # from exactly these.
        self._spec_ring: deque[dict] = deque(maxlen=self.window)
        self._spec_totals = {name: 0.0 for name in SPEC_SEGMENTS}
        self._spec_totals["wall"] = 0.0
        self._spec_rounds = 0
        self._spec_overlapped = 0
        self._spec_tokens = 0
        self.spec_profiled = 0
        # Persistent-loop books (engine/persistent/): per-harvest records
        # with telescoping PERSISTENT_SEGMENTS — the residency proof the
        # zero-dispatch loop is measured against.
        self._pers_ring: deque[dict] = deque(maxlen=self.window)
        self._pers_totals = {name: 0.0 for name in PERSISTENT_SEGMENTS}
        self._pers_totals["wall"] = 0.0
        self._pers_steps = 0
        self._pers_tokens = 0
        self.persistent_profiled = 0
        # In-loop sub-books (PERSISTENT_LOOP_SEGMENTS): "wall" here is
        # the loop_resident covered by windows that CARRIED a telemetry
        # snapshot — the denominator the sub-fractions telescope over.
        self._pers_loop_totals = {
            name: 0.0 for name in PERSISTENT_LOOP_SEGMENTS
        }
        self._pers_loop_totals["wall"] = 0.0
        self.persistent_loop_profiled = 0
        # Monotone resident-token counter beside the windowed books — the
        # SLO throughput floor windows THIS with its own baselines (the
        # windowed figure is non-monotone under ring eviction; see the
        # _cum comment above).
        self._pers_tokens_cum = 0
        # Decision-flow books: (XLA dispatches, decisions completed)
        # deltas booked at each completion window (engine.
        # _book_decision_flow). The windowed ratio is THE zero-dispatch
        # headline: 0.0 in persistent steady state, >= 1 on the dispatch
        # path. Dispatch deltas telescope exactly — every stats
        # "dispatches" bump lands in exactly one window — so the lifetime
        # sum of d_dispatches equals the engine's dispatch counter.
        self._flow_ring: deque[tuple[int, int]] = deque(maxlen=self.window)
        self._flow_dispatches = 0
        self._flow_decisions = 0
        self.closed = False

    # ------------------------------------------------------------- fences
    def on_submit(
        self,
        handle: Any,
        t_enter: float,
        t_exit: float,
        *,
        suffix_tokens: int,
        n_requests: int,
        prefix_len: int,
        cold_compile: bool,
    ) -> None:
        """submit_wave fencing: t_enter/t_exit bracket the jit dispatch
        (prompt packing + program enqueue + D2H kick)."""
        with self._lock:
            if len(self._open) > 64:
                # a leaked handle (harvest raised before reaching the
                # profiler fence) must not grow this map forever
                self._open.clear()
            self._open[id(handle)] = {
                "submit_enter": t_enter,
                "submit_exit": t_exit,
                "enqueued_at": None,
                "ready_at": None,
                "suffix_tokens": int(suffix_tokens),
                "n_requests": int(n_requests),
                "prefix_len": int(prefix_len),
                "cold_compile": bool(cold_compile),
            }

    def note_admission(self, handle: Any, oldest_enqueued_at: float) -> None:
        """Queue-side fence from engine/local.py: the oldest batch item's
        enqueue time (perf_counter) — the wave's queue_stall anchor."""
        with self._lock:
            st = self._open.get(id(handle))
            if st is not None:
                st["enqueued_at"] = float(oldest_enqueued_at)

    def note_ready(self, handle: Any) -> None:
        """The worker's is_ready() poll observed the device result landing
        (first call wins); on a blocking harvest the device_get return
        stands in for this edge."""
        now = self._clock()
        with self._lock:
            st = self._open.get(id(handle))
            if st is not None and st["ready_at"] is None:
                st["ready_at"] = now

    def on_harvest(
        self,
        handle: Any,
        t_enter: float,
        t_sync: float,
        t_exit: float,
        *,
        decode_tokens: int,
        model_calls: int,
        ready_at_entry: bool,
    ) -> None:
        """harvest_wave fencing: t_enter -> t_sync brackets the device_get
        (the block_until_ready boundary), t_sync -> t_exit the host-side
        token decode. Completes the wave record."""
        with self._lock:
            st = self._open.pop(id(handle), None)
        if st is None:
            return  # submitted before the profiler attached
        ready_at = st["ready_at"]
        if ready_at is None:
            # never observed by a poll: the result landed either before
            # harvest entry (charge the gap) or at the device_get return
            ready_at = t_enter if ready_at_entry else t_sync
        start = st["enqueued_at"]
        if start is None or start > st["submit_enter"]:
            start = st["submit_enter"]
        seg = {
            "queue_stall": max(st["submit_enter"] - start, 0.0),
            "dispatch": max(st["submit_exit"] - st["submit_enter"], 0.0),
            "dispatch_gap": max(t_enter - st["submit_exit"], 0.0),
            "host_sync": max(t_sync - t_enter, 0.0),
            "harvest": max(t_exit - t_sync, 0.0),
        }
        wall = max(t_exit - start, 0.0)
        seg["unattributed"] = max(wall - sum(seg.values()), 0.0)
        device = min(max(ready_at - st["submit_exit"], 0.0), wall)
        suffix_tokens = st["suffix_tokens"]
        tokens = suffix_tokens + int(decode_tokens)
        flops = self._wave_flops(
            st["prefix_len"], suffix_tokens, int(decode_tokens),
            st["n_requests"],
        )
        record = {
            "wave": 0,  # stamped under the lock below
            "n_requests": st["n_requests"],
            "cold_compile": st["cold_compile"],
            "wall_ms": wall * 1000.0,
            "segments_ms": {k: v * 1000.0 for k, v in seg.items()},
            "device_compute_ms": device * 1000.0,
            "suffix_tokens": suffix_tokens,
            "decode_tokens": int(decode_tokens),
            "model_calls": int(model_calls),
            "flops": flops,
        }
        with self._lock:
            self._wave_counter += 1
            record["wave"] = self._wave_counter
            # the aggregates are WINDOWED over the ring: an evicted wave's
            # contribution leaves the books, so segment_frac / mfu gauges
            # track the last `window` waves and a fresh regression moves
            # them immediately instead of drowning in lifetime history
            if len(self._ring) == self._ring.maxlen:
                old = self._ring[0]
                if not old["cold_compile"]:
                    for name in SEGMENTS:
                        self._totals[name] = max(
                            self._totals[name]
                            - old["segments_ms"].get(name, 0.0) / 1000.0,
                            0.0,
                        )
                    self._totals["device_compute"] = max(
                        self._totals["device_compute"]
                        - old["device_compute_ms"] / 1000.0,
                        0.0,
                    )
                    self._totals["wall"] = max(
                        self._totals["wall"] - old["wall_ms"] / 1000.0, 0.0
                    )
                    self._flops_total = max(
                        self._flops_total - old["flops"], 0.0
                    )
                    self._tokens_total = max(
                        self._tokens_total
                        - (old["suffix_tokens"] + old["decode_tokens"]),
                        0,
                    )
            self._ring.append(record)
            self.waves_profiled += 1
            # cold-compile waves hit the ring (they are real wall time the
            # operator should see) but stay out of the MFU aggregates —
            # jit time would poison the loss attribution exactly the way
            # it poisons the service-time EMA (engine/local.py)
            if not st["cold_compile"]:
                for name in SEGMENTS:
                    self._totals[name] += seg.get(name, 0.0)
                    self._cum[name] += seg.get(name, 0.0)
                self._totals["device_compute"] += device
                self._totals["wall"] += wall
                self._cum["wall"] += wall
                self._flops_total += flops
                self._tokens_total += tokens

    def note_prefix_prefill(self, tokens_prefilled: int, prefix_len: int) -> None:
        """A cluster-state prefix (re)prefill happened: `tokens_prefilled`
        is what was actually COMPUTED (0 on a cache hit; only the
        non-reused tail on an LCP-seeded / pinned-snapshot prefill), so
        the prefill-tokens-per-decision gauge credits delta encoding with
        exactly the work it skipped."""
        with self._lock:
            self._prefix_prefills.append(
                (int(tokens_prefilled), int(prefix_len))
            )

    def on_pack(
        self,
        *,
        wall_s: float,
        chunk_prefill_s: float,
        piggyback_s: float,
        n_prompts: int,
        tokens: int,
        chunks: int,
    ) -> None:
        """One packed admission completed dispatching (engine.admit_packed;
        the host never synced — segments are host-side enqueue walls).
        admission_pack = wall minus the measured dispatch segments (the
        packing/bookkeeping share); the identity sum(segments) == wall
        holds by construction and the acceptance test pins it."""
        wall = max(float(wall_s), 0.0)
        seg = {
            "chunk_prefill": max(float(chunk_prefill_s), 0.0),
            "decode_piggyback": max(float(piggyback_s), 0.0),
        }
        seg["admission_pack"] = max(
            wall - seg["chunk_prefill"] - seg["decode_piggyback"], 0.0
        )
        seg["unattributed"] = max(wall - sum(seg.values()), 0.0)
        record = {
            "pack": 0,  # stamped under the lock below
            "n_prompts": int(n_prompts),
            "tokens": int(tokens),
            "chunks": int(chunks),
            "wall_ms": wall * 1000.0,
            "segments_ms": {k: v * 1000.0 for k, v in seg.items()},
        }
        with self._lock:
            self.packs_profiled += 1
            record["pack"] = self.packs_profiled
            if len(self._pack_ring) == self._pack_ring.maxlen:
                old = self._pack_ring[0]
                for name in PACK_SEGMENTS:
                    self._pack_totals[name] = max(
                        self._pack_totals[name]
                        - old["segments_ms"].get(name, 0.0) / 1000.0,
                        0.0,
                    )
                self._pack_totals["wall"] = max(
                    self._pack_totals["wall"] - old["wall_ms"] / 1000.0, 0.0
                )
            self._pack_ring.append(record)
            for name in PACK_SEGMENTS:
                self._pack_totals[name] += seg[name]
            self._pack_totals["wall"] += wall

    def on_fused(
        self,
        *,
        wall_s: float,
        dispatch_s: float,
        sync_s: float,
        harvest_s: float,
        steps: int,
        tokens: int,
        chunks: int,
        ctx: float = 0.0,
    ) -> None:
        """One fused harvest completed (engine.step_fused / decode_fused).
        The three measured segments partition the wall by construction
        (consecutive perf_counter fences), so sum(segments) == wall holds
        exactly and the acceptance test pins it. `tokens` counts EMITTED
        tokens (pad-filtered, early-exit aware) — never chunk capacity —
        and `ctx` is the mean decode attention context for the FLOP books.
        """
        wall = max(float(wall_s), 0.0)
        seg = {
            "dispatch": max(float(dispatch_s), 0.0),
            "host_sync": max(float(sync_s), 0.0),
            "harvest": max(float(harvest_s), 0.0),
        }
        seg["unattributed"] = max(wall - sum(seg.values()), 0.0)
        flops = 0.0
        if self.cfg is not None and tokens > 0:
            flops = tokens * (
                matmul_flops_per_token(self.cfg)
                + attn_flops_per_token(self.cfg, max(float(ctx), 0.0))
            )
        record = {
            "harvest": 0,  # stamped under the lock below
            "chunks": int(chunks),
            "steps": int(steps),
            "tokens": int(tokens),
            "wall_ms": wall * 1000.0,
            "segments_ms": {k: v * 1000.0 for k, v in seg.items()},
            "flops": flops,
        }
        with self._lock:
            self.fused_profiled += 1
            record["harvest"] = self.fused_profiled
            if len(self._fused_ring) == self._fused_ring.maxlen:
                old = self._fused_ring[0]
                for name in FUSED_SEGMENTS:
                    self._fused_totals[name] = max(
                        self._fused_totals[name]
                        - old["segments_ms"].get(name, 0.0) / 1000.0,
                        0.0,
                    )
                self._fused_totals["wall"] = max(
                    self._fused_totals["wall"] - old["wall_ms"] / 1000.0, 0.0
                )
                self._fused_flops = max(self._fused_flops - old["flops"], 0.0)
                self._fused_tokens = max(
                    self._fused_tokens - old["tokens"], 0
                )
            self._fused_ring.append(record)
            for name in FUSED_SEGMENTS:
                self._fused_totals[name] += seg.get(name, 0.0)
            self._fused_totals["wall"] += wall
            self._fused_flops += flops
            self._fused_tokens += int(tokens)

    def on_spec(
        self,
        *,
        wall_s: float,
        draft_s: float,
        verify_s: float,
        rollback_s: float,
        rounds: int,
        overlapped_rounds: int,
        tokens: int,
        arm: str = "draft",
        disabled: bool = False,
    ) -> None:
        """One speculative request closed (spec/decoder.py — at
        completion, or at the auto-disable hand-off, in which case the
        record covers only the speculative phase). The three measured
        segments partition the wall by construction (consecutive
        perf_counter fences accumulated over the request's rounds), so
        sum(SPEC_SEGMENTS) == wall holds exactly and the acceptance test
        pins it. `overlapped_rounds` counts rounds whose proposal block
        was device-resident when the round began — the draft stream
        running in the shadow of the verify."""
        wall = max(float(wall_s), 0.0)
        seg = {
            "draft": max(float(draft_s), 0.0),
            "verify": max(float(verify_s), 0.0),
            "rollback": max(float(rollback_s), 0.0),
        }
        seg["unattributed"] = max(wall - sum(seg.values()), 0.0)
        record = {
            "request": 0,  # stamped under the lock below
            "arm": str(arm),
            "rounds": int(rounds),
            "overlapped_rounds": int(overlapped_rounds),
            "tokens": int(tokens),
            "disabled": bool(disabled),
            "wall_ms": wall * 1000.0,
            "segments_ms": {k: v * 1000.0 for k, v in seg.items()},
        }
        with self._lock:
            self.spec_profiled += 1
            record["request"] = self.spec_profiled
            if len(self._spec_ring) == self._spec_ring.maxlen:
                old = self._spec_ring[0]
                for name in SPEC_SEGMENTS:
                    self._spec_totals[name] = max(
                        self._spec_totals[name]
                        - old["segments_ms"].get(name, 0.0) / 1000.0,
                        0.0,
                    )
                self._spec_totals["wall"] = max(
                    self._spec_totals["wall"] - old["wall_ms"] / 1000.0, 0.0
                )
                self._spec_rounds = max(self._spec_rounds - old["rounds"], 0)
                self._spec_overlapped = max(
                    self._spec_overlapped - old["overlapped_rounds"], 0
                )
                self._spec_tokens = max(self._spec_tokens - old["tokens"], 0)
            self._spec_ring.append(record)
            for name in SPEC_SEGMENTS:
                self._spec_totals[name] += seg.get(name, 0.0)
            self._spec_totals["wall"] += wall
            self._spec_rounds += int(rounds)
            self._spec_overlapped += int(overlapped_rounds)
            self._spec_tokens += int(tokens)

    def on_persistent(
        self,
        *,
        wall_s: float,
        ring_wait_s: float,
        harvest_s: float,
        loop_resident_s: float,
        steps: int,
        tokens: int,
        batches: int,
        loop_segments: dict[str, float] | None = None,
    ) -> None:
        """One persistent-loop harvest window closed (engine.
        step_persistent): wall is the time since the previous harvest,
        ring_wait the TokenRing.drain block, harvest the host-side batch
        booking, loop_resident the remainder — device-resident serving
        with zero host involvement. The engine pre-clamps the measured
        segments to the wall, so sum(PERSISTENT_SEGMENTS) == wall holds
        exactly and the acceptance test pins it.

        `loop_segments` (optional) is the counter-delta decomposition of
        loop_resident into PERSISTENT_LOOP_SEGMENTS — already summing
        exactly to loop_resident_s (engine._decompose_loop_resident
        builds the last segment as the remainder); booked as-is, never
        renormalized, so the sub-family identity pin is end to end."""
        wall = max(float(wall_s), 0.0)
        seg = {
            "ring_wait": max(float(ring_wait_s), 0.0),
            "harvest": max(float(harvest_s), 0.0),
            "loop_resident": max(float(loop_resident_s), 0.0),
        }
        seg["unattributed"] = max(wall - sum(seg.values()), 0.0)
        record = {
            "harvest": 0,  # stamped under the lock below
            "batches": int(batches),
            "steps": int(steps),
            "tokens": int(tokens),
            "wall_ms": wall * 1000.0,
            "segments_ms": {k: v * 1000.0 for k, v in seg.items()},
        }
        if loop_segments is not None:
            record["loop_segments_ms"] = {
                name: max(float(loop_segments.get(name, 0.0)), 0.0) * 1000.0
                for name in PERSISTENT_LOOP_SEGMENTS
            }
        with self._lock:
            self.persistent_profiled += 1
            record["harvest"] = self.persistent_profiled
            if len(self._pers_ring) == self._pers_ring.maxlen:
                old = self._pers_ring[0]
                for name in PERSISTENT_SEGMENTS:
                    self._pers_totals[name] = max(
                        self._pers_totals[name]
                        - old["segments_ms"].get(name, 0.0) / 1000.0,
                        0.0,
                    )
                self._pers_totals["wall"] = max(
                    self._pers_totals["wall"] - old["wall_ms"] / 1000.0, 0.0
                )
                self._pers_steps = max(self._pers_steps - old["steps"], 0)
                self._pers_tokens = max(
                    self._pers_tokens - old["tokens"], 0
                )
                old_loop = old.get("loop_segments_ms")
                if old_loop is not None:
                    for name in PERSISTENT_LOOP_SEGMENTS:
                        self._pers_loop_totals[name] = max(
                            self._pers_loop_totals[name]
                            - old_loop.get(name, 0.0) / 1000.0,
                            0.0,
                        )
                    self._pers_loop_totals["wall"] = max(
                        self._pers_loop_totals["wall"]
                        - old["segments_ms"]["loop_resident"] / 1000.0,
                        0.0,
                    )
                    self.persistent_loop_profiled = max(
                        self.persistent_loop_profiled - 1, 0
                    )
            self._pers_ring.append(record)
            for name in PERSISTENT_SEGMENTS:
                self._pers_totals[name] += seg.get(name, 0.0)
            self._pers_totals["wall"] += wall
            self._pers_steps += int(steps)
            self._pers_tokens += int(tokens)
            self._pers_tokens_cum += int(tokens)
            if loop_segments is not None:
                for name in PERSISTENT_LOOP_SEGMENTS:
                    self._pers_loop_totals[name] += (
                        record["loop_segments_ms"][name] / 1000.0
                    )
                self._pers_loop_totals["wall"] += seg["loop_resident"]
                self.persistent_loop_profiled += 1

    def on_decision_flow(self, d_dispatches: int, d_decisions: int) -> None:
        """Book one completion window's (dispatch delta, decision delta).
        The engine calls this whenever decisions complete, with the XLA
        dispatches issued since the PREVIOUS completion window — deltas
        telescope, so the windowed ratio charges every dispatch to
        exactly one batch of decisions."""
        d_disp = max(int(d_dispatches), 0)
        d_done = max(int(d_decisions), 0)
        if d_done <= 0:
            return
        with self._lock:
            if len(self._flow_ring) == self._flow_ring.maxlen:
                old_disp, old_done = self._flow_ring[0]
                self._flow_dispatches = max(
                    self._flow_dispatches - old_disp, 0
                )
                self._flow_decisions = max(
                    self._flow_decisions - old_done, 0
                )
            self._flow_ring.append((d_disp, d_done))
            self._flow_dispatches += d_disp
            self._flow_decisions += d_done

    def dispatches_per_decision(self) -> float | None:
        """Windowed XLA dispatches per completed decision — 0.0 in
        persistent steady state (the zero-dispatch pin), >= 1 on the
        dispatch path. None until a completion window has been booked."""
        with self._lock:
            if self._flow_decisions <= 0:
                return None
            return round(self._flow_dispatches / self._flow_decisions, 4)

    def _prefill_tokens_per_decision_locked(self) -> float | None:
        """Windowed prefill tokens per decision: (wave suffix tokens +
        packed tokens + prefix tokens actually prefilled) / decisions.
        Caller holds the lock."""
        decisions = sum(r["n_requests"] for r in self._ring) + sum(
            r["n_prompts"] for r in self._pack_ring
        )
        if decisions <= 0:
            return None
        tokens = (
            sum(r["suffix_tokens"] for r in self._ring)
            + sum(r["tokens"] for r in self._pack_ring)
            + sum(t for t, _ in self._prefix_prefills)
        )
        return tokens / decisions

    # -------------------------------------------------------------- flops
    def _wave_flops(
        self,
        prefix_len: int,
        suffix_tokens: int,
        decode_tokens: int,
        n_requests: int = 1,
    ) -> float:
        """Achieved FLOPs of one wave: suffix prefill + block decode, both
        attending to the shared prefix (mean PER-REQUEST context ~ prefix +
        half that request's suffix+emission — same estimator bench.py's
        MFU uses; the wave total must be apportioned or a batched wave's
        attention term is overstated n_requests-fold)."""
        if self.cfg is None:
            return 0.0
        n = suffix_tokens + decode_tokens
        if n <= 0:
            return 0.0
        per_req = n / max(int(n_requests), 1)
        ctx = prefix_len + (per_req / 2.0)
        return n * (
            matmul_flops_per_token(self.cfg)
            + attn_flops_per_token(self.cfg, ctx)
        )

    # ------------------------------------------------------------- exports
    def _mfu(
        self, flops: float, wall: float, device: float, totals: dict
    ) -> dict | None:
        """The decomposition: mfu_decode + sum(loss terms) ~= mfu_device.

        The device is busy during [dispatch end, ready], which overlaps
        dispatch_gap and host_sync; each segment's loss term charges its
        NON-OVERLAPPED (device-idle) share, so the identity holds by
        construction: loss[s] = mfu_device * idle_s / wall and
        sum(idle) + device = wall. `totals` is the caller's copy taken
        under ONE lock acquisition together with flops/wall/device — a
        re-read here could include a wave the other figures don't."""
        if not self.peak_flops or wall <= 0 or flops <= 0:
            return None
        mfu = flops / wall / self.peak_flops
        if device <= 0:
            return {"decode": round(mfu, 5)}
        mfu_device = flops / device / self.peak_flops
        seg = {name: totals[name] for name in SEGMENTS}
        # device busy overlaps the gap first, then the sync window
        overlap_gap = min(seg["dispatch_gap"], device)
        overlap_sync = min(seg["host_sync"], device - overlap_gap)
        idle = dict(seg)
        idle["dispatch_gap"] = max(seg["dispatch_gap"] - overlap_gap, 0.0)
        idle["host_sync"] = max(seg["host_sync"] - overlap_sync, 0.0)
        loss = {
            name: round(mfu_device * idle_s / wall, 5)
            for name, idle_s in idle.items()
            if idle_s > 0
        }
        return {
            "decode": round(mfu, 5),
            "device": round(mfu_device, 5),
            "busy_frac": round(device / wall, 4),
            "loss": loss,
        }

    def snapshot(self) -> dict:
        """The /debug/profile payload: windowed segment totals/means, the
        MFU decomposition, and the per-wave ring."""
        with self._lock:
            ring = list(self._ring)
            totals = dict(self._totals)
            flops = self._flops_total
            tokens = self._tokens_total
            waves = self.waves_profiled
            pack_ring = list(self._pack_ring)
            pack_totals = dict(self._pack_totals)
            packs = self.packs_profiled
            fused_ring = list(self._fused_ring)
            fused_totals = dict(self._fused_totals)
            fused_flops = self._fused_flops
            fused_tokens = self._fused_tokens
            fused = self.fused_profiled
            spec_ring = list(self._spec_ring)
            spec_totals = dict(self._spec_totals)
            spec_rounds = self._spec_rounds
            spec_overlapped = self._spec_overlapped
            spec_tokens = self._spec_tokens
            spec = self.spec_profiled
            pers_ring = list(self._pers_ring)
            pers_totals = dict(self._pers_totals)
            pers_steps = self._pers_steps
            pers_tokens = self._pers_tokens
            pers = self.persistent_profiled
            pers_loop_totals = dict(self._pers_loop_totals)
            pers_loop = self.persistent_loop_profiled
            flow_disp = self._flow_dispatches
            flow_done = self._flow_decisions
            tpd = self._prefill_tokens_per_decision_locked()
        wall = totals["wall"]
        n_warm = sum(1 for r in ring if not r["cold_compile"])
        out: dict[str, Any] = {
            "waves_profiled": waves,
            "window": self.window,
            "device_kind": self.device_kind,
            "peak_bf16_tflops": (
                self.peak_flops / 1e12 if self.peak_flops else None
            ),
            "wall_ms_total": round(wall * 1000.0, 3),
            "segments_ms_total": {
                name: round(totals[name] * 1000.0, 3) for name in SEGMENTS
            },
            "device_compute_ms_total": round(
                totals["device_compute"] * 1000.0, 3
            ),
            "segment_frac": {
                name: round(totals[name] / wall, 4) if wall > 0 else 0.0
                for name in SEGMENTS
            },
            "coverage_frac": (
                round(
                    sum(totals[n] for n in SEGMENTS if n != "unattributed")
                    / wall,
                    4,
                )
                if wall > 0
                else 0.0
            ),
            "tokens": tokens,
            "achieved_tflops": (
                round(flops / wall / 1e12, 4) if wall > 0 else 0.0
            ),
            "warm_waves_in_window": n_warm,
            "ring": ring,
        }
        mfu = self._mfu(flops, wall, totals["device_compute"], totals)
        if mfu is not None:
            out["mfu"] = mfu
        if packs:
            pack_wall = pack_totals["wall"]
            out["packs"] = {
                "packs_profiled": packs,
                "wall_ms_total": round(pack_wall * 1000.0, 3),
                "segments_ms_total": {
                    name: round(pack_totals[name] * 1000.0, 3)
                    for name in PACK_SEGMENTS
                },
                "segment_frac": {
                    name: (
                        round(pack_totals[name] / pack_wall, 4)
                        if pack_wall > 0
                        else 0.0
                    )
                    for name in PACK_SEGMENTS
                },
                "ring": pack_ring,
            }
        if fused:
            fused_wall = fused_totals["wall"]
            fused_out: dict[str, Any] = {
                "harvests_profiled": fused,
                "tokens": fused_tokens,
                "wall_ms_total": round(fused_wall * 1000.0, 3),
                "segments_ms_total": {
                    name: round(fused_totals[name] * 1000.0, 3)
                    for name in FUSED_SEGMENTS
                },
                "segment_frac": {
                    name: (
                        round(fused_totals[name] / fused_wall, 4)
                        if fused_wall > 0
                        else 0.0
                    )
                    for name in FUSED_SEGMENTS
                },
                "ring": fused_ring,
            }
            if fused_wall > 0:
                fused_out["tokens_per_s"] = round(
                    fused_tokens / fused_wall, 1
                )
                fused_out["achieved_tflops"] = round(
                    fused_flops / fused_wall / 1e12, 4
                )
                if self.peak_flops and fused_flops > 0:
                    fused_out["mfu_decode"] = round(
                        fused_flops / fused_wall / self.peak_flops, 5
                    )
            out["fused"] = fused_out
        if spec:
            spec_wall = spec_totals["wall"]
            spec_out: dict[str, Any] = {
                "requests_profiled": spec,
                "tokens": spec_tokens,
                "rounds": spec_rounds,
                "overlapped_rounds": spec_overlapped,
                "overlap_fraction": (
                    round(spec_overlapped / spec_rounds, 4)
                    if spec_rounds > 0
                    else 0.0
                ),
                "wall_ms_total": round(spec_wall * 1000.0, 3),
                "segments_ms_total": {
                    name: round(spec_totals[name] * 1000.0, 3)
                    for name in SPEC_SEGMENTS
                },
                "segment_frac": {
                    name: (
                        round(spec_totals[name] / spec_wall, 4)
                        if spec_wall > 0
                        else 0.0
                    )
                    for name in SPEC_SEGMENTS
                },
                "ring": spec_ring,
            }
            if spec_wall > 0:
                spec_out["tokens_per_s"] = round(spec_tokens / spec_wall, 1)
            out["spec"] = spec_out
        if pers:
            pers_wall = pers_totals["wall"]
            pers_out: dict[str, Any] = {
                "harvests_profiled": pers,
                "steps": pers_steps,
                "tokens": pers_tokens,
                "wall_ms_total": round(pers_wall * 1000.0, 3),
                "segments_ms_total": {
                    name: round(pers_totals[name] * 1000.0, 3)
                    for name in PERSISTENT_SEGMENTS
                },
                "segment_frac": {
                    name: (
                        round(pers_totals[name] / pers_wall, 4)
                        if pers_wall > 0
                        else 0.0
                    )
                    for name in PERSISTENT_SEGMENTS
                },
                "ring": pers_ring,
            }
            if pers_wall > 0:
                pers_out["tokens_per_s"] = round(pers_tokens / pers_wall, 1)
            if pers_loop:
                loop_wall = pers_loop_totals["wall"]
                pers_out["loop_windows_profiled"] = pers_loop
                pers_out["loop_segments_ms_total"] = {
                    name: round(pers_loop_totals[name] * 1000.0, 3)
                    for name in PERSISTENT_LOOP_SEGMENTS
                }
                pers_out["loop_segment_frac"] = {
                    name: (
                        round(pers_loop_totals[name] / loop_wall, 4)
                        if loop_wall > 0
                        else 0.0
                    )
                    for name in PERSISTENT_LOOP_SEGMENTS
                }
            out["persistent"] = pers_out
        if flow_done > 0:
            out["dispatches_per_decision"] = round(
                flow_disp / flow_done, 4
            )
        if tpd is not None:
            out["prefill_tokens_per_decision"] = round(tpd, 2)
        return out

    def gauges(self) -> dict[str, float]:
        """Flat numeric view for /metrics (observability/metrics._flatten
        renders each as a llm_scheduler_engine_profile_* gauge)."""
        with self._lock:
            totals = dict(self._totals)
            cum = dict(self._cum)
            flops = self._flops_total
            waves = self.waves_profiled
            pack_totals = dict(self._pack_totals)
            packs = self.packs_profiled
            fused_totals = dict(self._fused_totals)
            fused_flops = self._fused_flops
            fused = self.fused_profiled
            spec_totals = dict(self._spec_totals)
            spec_rounds = self._spec_rounds
            spec_overlapped = self._spec_overlapped
            spec = self.spec_profiled
            pers_totals = dict(self._pers_totals)
            pers = self.persistent_profiled
            pers_loop_totals = dict(self._pers_loop_totals)
            pers_loop = self.persistent_loop_profiled
            flow_disp = self._flow_dispatches
            flow_done = self._flow_decisions
            tpd = self._prefill_tokens_per_decision_locked()
        wall = totals["wall"]
        out: dict[str, float] = {"waves_profiled": float(waves)}
        for name in SEGMENTS:
            out[f"{name}_frac"] = (
                round(totals[name] / wall, 4) if wall > 0 else 0.0
            )
            # monotone ms counters (module __init__ comment): what the
            # SLO engine's windowed deltas consume
            out[f"{name}_ms_total"] = round(cum[name] * 1000.0, 3)
        out["wall_ms_cum_total"] = round(cum["wall"] * 1000.0, 3)
        if packs:
            out["packs_profiled"] = float(packs)
            pack_wall = pack_totals["wall"]
            for name in PACK_SEGMENTS:
                out[f"pack_{name}_frac"] = (
                    round(pack_totals[name] / pack_wall, 4)
                    if pack_wall > 0
                    else 0.0
                )
        if fused:
            out["fused_profiled"] = float(fused)
            fused_wall = fused_totals["wall"]
            for name in FUSED_SEGMENTS:
                out[f"fused_{name}_frac"] = (
                    round(fused_totals[name] / fused_wall, 4)
                    if fused_wall > 0
                    else 0.0
                )
            if self.peak_flops and fused_wall > 0 and fused_flops > 0:
                out["fused_mfu_decode"] = round(
                    fused_flops / fused_wall / self.peak_flops, 5
                )
        if spec:
            out["spec_profiled"] = float(spec)
            spec_wall = spec_totals["wall"]
            for name in SPEC_SEGMENTS:
                out[f"spec_{name}_frac"] = (
                    round(spec_totals[name] / spec_wall, 4)
                    if spec_wall > 0
                    else 0.0
                )
            out["spec_overlap_frac"] = (
                round(spec_overlapped / spec_rounds, 4)
                if spec_rounds > 0
                else 0.0
            )
        if pers:
            out["persistent_profiled"] = float(pers)
            pers_wall = pers_totals["wall"]
            for name in PERSISTENT_SEGMENTS:
                out[f"persistent_{name}_frac"] = (
                    round(pers_totals[name] / pers_wall, 4)
                    if pers_wall > 0
                    else 0.0
                )
            if pers_loop:
                loop_wall = pers_loop_totals["wall"]
                for name in PERSISTENT_LOOP_SEGMENTS:
                    out[f"persistent_loop_{name}_frac"] = (
                        round(pers_loop_totals[name] / loop_wall, 4)
                        if loop_wall > 0
                        else 0.0
                    )
        if flow_done > 0:
            out["dispatches_per_decision"] = round(
                flow_disp / flow_done, 4
            )
        if tpd is not None:
            out["prefill_tokens_per_decision"] = round(tpd, 2)
        out["device_compute_frac"] = (
            round(totals["device_compute"] / wall, 4) if wall > 0 else 0.0
        )
        if wall > 0:
            out["achieved_tflops"] = round(flops / wall / 1e12, 4)
        mfu = self._mfu(flops, wall, totals["device_compute"], totals)
        if mfu is not None:
            out["mfu_decode"] = mfu["decode"]
            if "device" in mfu:
                out["mfu_device"] = mfu["device"]
            for name, value in (mfu.get("loss") or {}).items():
                out[f"mfu_loss_{name}"] = value
        return out

    def persistent_gauges(self) -> dict[str, float]:
        """The `llm_scheduler_persistent_*` gauge family: a flat numeric
        subtree the metrics server mounts at stats["persistent"] (the
        Prometheus renderer prefixes flattened paths with
        llm_scheduler_). Fleet-merge aware by NAMING: `*_frac` leaves
        average across replicas (fleetview._RATIO_SUFFIXES), plain
        counters/rates SUM — a fleet's resident tok/s is the sum of its
        replicas', its segment mix the mean. `tokens_total` is the
        monotone counter the SLO throughput floor windows."""
        with self._lock:
            pers_totals = dict(self._pers_totals)
            pers = self.persistent_profiled
            pers_tokens = self._pers_tokens
            pers_steps = self._pers_steps
            pers_loop_totals = dict(self._pers_loop_totals)
            pers_loop = self.persistent_loop_profiled
            tokens_cum = self._pers_tokens_cum
        out: dict[str, float] = {
            "harvests": float(pers),
            "steps": float(pers_steps),
            "tokens": float(pers_tokens),
            "tokens_total": float(tokens_cum),
            "loop_windows": float(pers_loop),
        }
        pers_wall = pers_totals["wall"]
        out["resident_tokens_per_s"] = (
            round(pers_tokens / pers_wall, 1) if pers_wall > 0 else 0.0
        )
        for name in PERSISTENT_SEGMENTS:
            out[f"{name}_frac"] = (
                round(pers_totals[name] / pers_wall, 4)
                if pers_wall > 0
                else 0.0
            )
        loop_wall = pers_loop_totals["wall"]
        for name in PERSISTENT_LOOP_SEGMENTS:
            out[f"loop_{name}_frac"] = (
                round(pers_loop_totals[name] / loop_wall, 4)
                if loop_wall > 0
                else 0.0
            )
        return out

    def close(self) -> None:
        """Flush any in-flight fence state (waves that will never harvest —
        backend shutdown fails them upstream) so shutdown leaves no
        half-open records; idempotent."""
        with self._lock:
            self._open.clear()
            self.closed = True
