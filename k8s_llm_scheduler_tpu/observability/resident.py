"""Device-resident telemetry plane for the persistent serving loop.

The persistent `lax.while_loop` program (engine/persistent/loop.py)
deleted the per-step dispatch boundaries the profiler fences on, so
steady-state serving books everything into one opaque `loop_resident`
segment. This module restores attribution WITHOUT reintroducing
dispatches:

- A device-resident COUNTER BLOCK rides in the loop carry (indices
  below): outer iterations, decode steps, admits taken, emissions
  pushed, command-ring empty polls, idle chunks, plus per-slot token
  counts and admission/first-emission iteration stamps. Updates are
  pure carried-array arithmetic inside the already-traced program —
  zero extra dispatches, zero extra callbacks.
- The counters leave the device by PIGGYBACKING on the loop's existing
  push io_callback (ordered callbacks inside `lax.cond` are the thing
  the loop design avoids, so telemetry must not add one). The host
  edge publishes a StatsSnapshot to the StatsRing below at a low,
  host-controlled cadence (PersistentServer.stats_every).
- StatsRing is the TokenRing's discipline applied to telemetry:
  bounded, seq-stamped at put, seq-VERIFIED at drain — losing a stats
  window is a loud protocol error, never a silent gap in the books.
  The server publishes via `put_latest` (drop-oldest, counted) so an
  undrained telemetry consumer can never backpressure-stall the
  serving loop itself; the blocking `put` exists for symmetry and is
  pinned by the same unit suite as TokenRing.put.
- BlackBox is the wedge forensics ring: the last-N per-push iteration
  snapshots (counters, ring cursors, slot-liveness bitmap — NO
  timestamps, so a dump is byte-stable across replays), dumped on
  watchdog latch or quiesce to /debug/blackbox and into the chaos
  trace under the `persistent-wedge` regime.

Everything here that the push callback reaches (StatsRing.put_latest,
BlackBox.record) is pure numpy + threading — graftlint's
dispatch-in-persistent-path rule sweeps this module via _device_push.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

# Device counter-block indices (int32 vector carried in the loop state).
CTR_ITERS = 0        # outer loop iterations (one poll+chunk+push each)
CTR_STEPS = 1        # decode steps actually run (sum of steps_run)
CTR_ADMITS = 2       # in-loop admissions taken (OP_ADMIT polls)
CTR_EMITTED = 3      # non-pad tokens written to the emission buffer
CTR_EMPTY_POLLS = 4  # polls that returned OP_NOOP (command ring empty)
CTR_IDLE_CHUNKS = 5  # iterations whose decode chunk ran zero steps
N_COUNTERS = 6

COUNTER_NAMES = (
    "iters",
    "steps",
    "admits",
    "emitted",
    "empty_polls",
    "idle_chunks",
)


def counters_dict(ctr: np.ndarray) -> dict[str, int]:
    """Name the counter vector (device export order is the index order)."""
    return {name: int(ctr[i]) for i, name in enumerate(COUNTER_NAMES)}


@dataclasses.dataclass
class StatsSnapshot:
    """One telemetry window: cumulative device counters at a push edge,
    merged with the host-side ring books the device cannot count (a
    token-ring stall blocks INSIDE the push callback — only the host
    sees it)."""

    seq: int                  # monotonic snapshot number (gap = loud error)
    counters: np.ndarray      # [N_COUNTERS] int64 cumulative device counters
    slot_tokens: np.ndarray   # [M] tokens emitted per slot (current occupant)
    admit_iter: np.ndarray    # [M] iteration stamp of the slot's admission
    first_emit: np.ndarray    # [M] iteration of first emission (-1 pending)
    pushes: int = 0           # token-ring pushes at snapshot time
    token_stalls: int = 0     # token-ring backpressure stalls (host books)
    cmd_stalls: int = 0       # command-ring feeder stalls (host books)
    cmd_depth: int = 0
    token_depth: int = 0


class StatsRing:
    """Bounded device->host telemetry stream, TokenRing discipline.

    `put` blocks when full (the mirror of emission backpressure, unit-
    pinned); `put_latest` never blocks — it drops the OLDEST snapshot,
    advances the take cursor past it, and counts the drop, so telemetry
    can never stall the serving loop while staying seq-verified: drain
    still proves no snapshot was lost SILENTLY."""

    def __init__(self, capacity: int = 64) -> None:
        if capacity < 1:
            raise ValueError("StatsRing capacity must be >= 1")
        self.capacity = int(capacity)
        self._items: deque[StatsSnapshot] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._next_seq = 0    # assigned by put (device side)
        self._take_seq = 0    # checked by drain (host side)
        self.stalls = 0       # blocking puts that had to wait on a full ring
        self.dropped = 0      # put_latest evictions of the oldest snapshot
        self.pushed = 0

    def put(
        self, snap: StatsSnapshot, stop_check: Callable[[], bool] | None = None
    ) -> bool:
        """Blocking publish: waits for space (polling `stop_check` like
        TokenRing.put so a forced drain can unwedge it); returns False
        when stopped, True on enqueue."""
        with self._cond:
            if len(self._items) >= self.capacity:
                self.stalls += 1
            while len(self._items) >= self.capacity and not self._closed:
                if stop_check is not None and stop_check():
                    return False
                self._cond.wait(0.05)
            if self._closed:
                raise RuntimeError("stats ring closed")
            snap.seq = self._next_seq
            self._next_seq += 1
            self._items.append(snap)
            self.pushed += 1
            self._cond.notify_all()
            return True

    def put_latest(self, snap: StatsSnapshot) -> None:
        """Non-blocking publish: a full ring evicts its OLDEST snapshot
        (freshest-wins — stats, unlike tokens, are superseded by the
        next cumulative window) and advances the take cursor so the
        drain-side seq check stays consistent. The eviction is counted,
        never silent."""
        with self._cond:
            if self._closed:
                raise RuntimeError("stats ring closed")
            if len(self._items) >= self.capacity:
                old = self._items.popleft()
                self._take_seq = max(self._take_seq, old.seq + 1)
                self.dropped += 1
            snap.seq = self._next_seq
            self._next_seq += 1
            self._items.append(snap)
            self.pushed += 1
            self._cond.notify_all()

    def drain(self, timeout_s: float = 0.0) -> list[StatsSnapshot]:
        """Host-side harvest, blocking up to `timeout_s` for the first
        snapshot. Seq-verified: a gap or repeat (beyond counted
        put_latest evictions, whose cursor advance keeps the check
        consistent) raises loudly."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while not self._items:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return []
                self._cond.wait(remaining)
            out = list(self._items)
            self._items.clear()
            self._cond.notify_all()
            for s in out:
                if s.seq != self._take_seq:
                    raise RuntimeError(
                        f"stats ring sequence break: got snapshot {s.seq}, "
                        f"expected {self._take_seq} (lost or duplicated "
                        f"telemetry)"
                    )
                self._take_seq += 1
        return out

    def clear_parked(self) -> int:
        """Drop every undelivered snapshot, advancing the cursor (the
        relaunch path: stale windows from a drained residency must not
        be booked against the new one)."""
        with self._cond:
            dropped = len(self._items)
            for s in self._items:
                self._take_seq = max(self._take_seq, s.seq + 1)
            self._items.clear()
            self._cond.notify_all()
            return dropped

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class BlackBox:
    """Bounded ring of the last-N iteration snapshots — the wedge
    forensics the watchdog dumps when the loop stops heartbeating.

    Snapshots are plain dicts of ints (counters, ring cursors, a slot
    liveness bitmap) with NO wall-clock fields: the dump is a pure
    function of the served sequence, which is what lets the chaos
    `persistent-wedge` regime pin it byte-identical across replays."""

    def __init__(self, depth: int = 64) -> None:
        if depth < 1:
            raise ValueError("BlackBox depth must be >= 1")
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.depth)
        self._recorded = 0

    def record(self, snap: dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(snap)
            self._recorded += 1

    @property
    def recorded(self) -> int:
        with self._lock:
            return self._recorded

    def dump(self, reason: str = "quiesce") -> dict[str, Any]:
        """Stable, JSON-ready view: bounded snapshot list plus the books
        needed to read it (depth, total recorded, dump reason)."""
        with self._lock:
            return {
                "reason": reason,
                "depth": self.depth,
                "recorded": self._recorded,
                "snapshots": [dict(s) for s in self._ring],
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0


def canonical_blackbox_bytes(dump: dict[str, Any]) -> bytes:
    """Canonical byte encoding of a black-box dump — the byte-identity
    pin the chaos regime replays against (same discipline as
    chaos.trace.canonical_chaos_bytes)."""
    return json.dumps(
        dump, sort_keys=True, separators=(",", ":"), default=str
    ).encode()


def liveness_bitmap(act: np.ndarray) -> int:
    """Pack a slot-liveness bool vector into one int (LSB = slot 0) —
    the black-box's fixed-size view of which slots were alive."""
    bits = 0
    for i, alive in enumerate(np.asarray(act).astype(bool).tolist()):
        if alive:
            bits |= 1 << i
    return bits
