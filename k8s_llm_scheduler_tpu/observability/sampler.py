"""Background engine telemetry sampler.

The engine's counters (engine/engine.py `stats`) are lifetime totals — a
scrape sees "4M decode tokens" but not "the engine sat at 12% batch
occupancy through the burst that just missed its SLO". This sampler turns
the totals into RING-BUFFERED TIME SERIES: a daemon thread snapshots the
engine every `interval_s` and derives

- `batch_occupancy`     in-flight paged slots / max_slots
- `kv_page_util`        allocated KV pages / pool size
- `prefix_cache_hit_rate`  prefix hits / (hits + prefills), lifetime ratio
- `tokens_per_s`        decode-token delta / wall delta (window rate);
                        counts EMITTED tokens (the engine books only
                        pad-filtered harvested tokens, exact under the
                        fused runtime's early-exiting chunks). A window
                        in which NO harvest sync landed reports None —
                        under fused chunked harvest the device may be
                        mid-chunk with tokens not yet visible, and a
                        fabricated 0.0 would saw-tooth the gauge at the
                        harvest cadence instead of measuring a rate.
- `hbm_used_frac`       device bytes_in_use / bytes_limit (None off-TPU)

`latest()` feeds /metrics as gauges; `series()` backs /debug/engine with
the full window, so "what did occupancy look like during the burst?" is
answerable after the fact without a dashboard stack. Sampling is read-only
against GIL-atomic engine state (dict reads, int reads) — no locks are
taken on the engine's hot path, same discipline as the stats providers.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any

logger = logging.getLogger(__name__)

SERIES = (
    "batch_occupancy",
    "kv_page_util",
    "prefix_cache_hit_rate",
    "tokens_per_s",
    "hbm_used_frac",
)


class EngineSampler:
    """Periodic sampler over one InferenceEngine (or anything shaped like
    it: `max_slots`, `free_slots`, `kv.pages_free`, `kv.num_pages`,
    `stats` dict). `clock` is injectable for deterministic tests."""

    def __init__(
        self,
        engine: Any,
        interval_s: float = 1.0,
        window: int = 600,
        clock=time.monotonic,
    ) -> None:
        self.engine = engine
        self.interval_s = max(0.05, float(interval_s))
        self.window = max(2, int(window))
        self._clock = clock
        self._lock = threading.Lock()
        self._series: dict[str, deque[tuple[float, float | None]]] = {
            name: deque(maxlen=self.window) for name in SERIES
        }
        self._last_tokens: tuple[float, int, int] | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples_taken = 0

    # ------------------------------------------------------------- sampling
    def _hbm_used_frac(self) -> float | None:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            return None
        if not stats:
            return None  # CPU backends return None/{}
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if not used or not limit:
            return None
        return used / limit

    def sample_once(self) -> dict[str, float | None]:
        """Take one sample and append it to every series. Public so tests
        (and the /debug handler on a cold sampler) can tick explicitly."""
        eng = self.engine
        stats = dict(getattr(eng, "stats", {}) or {})
        out: dict[str, float | None] = {}

        max_slots = getattr(eng, "max_slots", 0) or 0
        free = getattr(eng, "free_slots", max_slots)
        out["batch_occupancy"] = (
            (max_slots - free) / max_slots if max_slots else None
        )

        kv = getattr(eng, "kv", None)
        num_pages = getattr(kv, "num_pages", 0) or 0
        pages_free = getattr(kv, "pages_free", num_pages)
        out["kv_page_util"] = (
            (num_pages - pages_free) / num_pages if num_pages else None
        )

        hits = stats.get("prefix_hits", 0)
        fills = stats.get("prefix_prefills", 0)
        out["prefix_cache_hit_rate"] = (
            hits / (hits + fills) if (hits + fills) else None
        )

        out["hbm_used_frac"] = self._hbm_used_frac()

        tokens = int(stats.get("decode_tokens", 0))
        # Harvest progress marker: dispatch-path harvests bump `syncs`,
        # but persistent-loop harvests are RING traffic — zero dispatches,
        # zero syncs (the whole point of engine/persistent/). Folding the
        # chunk counter in means resident emissions advance the rate
        # baseline too; without it steady-state serving read ~0 tok/s in
        # /debug/engine (every window looked "no harvest landed").
        syncs = int(stats.get("syncs", 0)) + int(
            stats.get("persistent_chunks", 0)
        )
        # The rate baseline, clock read, and ring appends share ONE lock
        # acquisition: the background thread and /debug/engine's
        # cold-sample path (handler threads) may sample concurrently, and
        # an unguarded read-modify-write of _last_tokens would compute a
        # rate against a stale baseline — while taking `now` inside the
        # lock keeps ring timestamps monotone (series() renders ages
        # relative to the last entry and assumes it is newest).
        with self._lock:
            now = self._clock()
            if self._last_tokens is not None:
                t_prev, n_prev, s_prev = self._last_tokens
                dt = now - t_prev
                if dt <= 0:
                    out["tokens_per_s"] = None
                elif syncs == s_prev and tokens == n_prev:
                    # No harvest landed in this window: under fused
                    # chunked harvest the device may be mid-chunk with
                    # emitted tokens not yet host-visible — the rate is
                    # UNKNOWN, not zero, and the baseline is NOT advanced:
                    # the next synced sample reports the exact emitted
                    # rate over the whole elapsed span, so tokens decoded
                    # during unsynced windows are never misattributed.
                    # (A window WITH a sync and zero new tokens is
                    # genuine idle and reports 0.0.)
                    out["tokens_per_s"] = None
                else:
                    out["tokens_per_s"] = max(tokens - n_prev, 0) / dt
                    self._last_tokens = (now, tokens, syncs)
            else:
                out["tokens_per_s"] = None
                self._last_tokens = (now, tokens, syncs)
            self.samples_taken += 1
            for name in SERIES:
                self._series[name].append((now, out[name]))
        return out

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # one bad sample (engine mid-teardown) must not kill the
                # sampler thread for the process lifetime
                logger.exception("engine telemetry sample failed")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        # restartable: stop() leaves the event set, and a thread started
        # against a set event would exit its first wait() immediately
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-sampler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -------------------------------------------------------------- exports
    def latest(self) -> dict[str, float]:
        """Most recent non-None value per series — the /metrics gauges."""
        out: dict[str, float] = {}
        with self._lock:
            for name, ring in self._series.items():
                for _, value in reversed(ring):
                    if value is not None:
                        out[name] = round(value, 6)
                        break
            out["samples_taken"] = self.samples_taken
        return out

    def series(self) -> dict[str, Any]:
        """The full ring per series for /debug/engine: [[t, value], ...]
        with t relative to the newest sample (ages in seconds — wall-clock
        anchoring is the caller's concern, monotonic is ours)."""
        with self._lock:
            rings = {name: list(ring) for name, ring in self._series.items()}
        newest = max(
            (ring[-1][0] for ring in rings.values() if ring), default=0.0
        )
        return {
            "interval_s": self.interval_s,
            "window": self.window,
            "samples_taken": self.samples_taken,
            "series": {
                name: [
                    [round(t - newest, 3), value] for t, value in ring
                ]
                for name, ring in rings.items()
            },
        }
