"""SLO burn-rate engine: declarative objectives over windowed telemetry.

PR 4 gave the system per-decision percentiles (PhaseRecorder histograms,
windowed via `delta_hist`) and PR 6 made it a fleet — but nothing turned
those numbers into a SERVING-LEVEL signal: "is the error budget burning
fast enough that a human (or the canary gate, or the circuit breaker)
should act?" This module is that layer.

Objectives are declared in config.yaml (`slo.objectives`) and evaluated
over MULTI-WINDOW BURN RATES — the standard fast+slow pairing: the fast
window (default 5m) catches a sharp regression in minutes, the slow
window (default 1h) keeps a brief blip from paging anyone; a trip
requires BOTH to exceed their thresholds (defaults 14.4x / 6x, the
classic page-severity pairing). Three objective kinds:

- `latency`:   "phase X under T ms for all but `budget` of events" —
  violation fraction comes from windowed histogram bucket deltas
  (observability/trace.delta_hist over the fixed shared bucket ladder).
  Counting is CONSERVATIVE: an event counts as a violation only when its
  bucket's LOWER bound >= threshold, so bucket quantization can delay a
  trip by one 2x bucket but can never fire a false one (the same
  discipline rollout/canary's trip_decide_p99_ms uses).
- `error_rate`: numerator/denominator counter deltas (dotted stat paths,
  e.g. `failed_bindings` over `total_scheduled`) against a budget.
- `throughput`: a counter's windowed rate against a floor (e.g. fleet
  decisions/s); burn = floor/rate, thresholds default to 1.0.

Trips surface four ways: /debug/slo (full state), Prometheus gauges
(`llm_scheduler_slo_*`), a burn-in trip input for rollout/canary.py
(an open canary burn-in rolls back immediately on an SLO trip), and an
ADVISORY hook into core/breaker.py (`CircuitBreaker.slo_advisory` —
recorded and surfaced, never forcing the state machine: the breaker
guards backend health, and a latency SLO burn is evidence, not proof, of
a backend fault). `on_trip` callbacks fire on the RISING edge only.

Evaluation is pull-based (`evaluate()`), with an optional background
ticker thread whose lifecycle matches EngineSampler (start/stop with
join; MetricsServer.stop() stops it too).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Callable

from k8s_llm_scheduler_tpu.observability.trace import (
    BUCKET_BOUNDS_S,
    delta_hist,
)

logger = logging.getLogger(__name__)

KINDS = ("latency", "error_rate", "throughput")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective (config.yaml `slo.objectives` entry)."""

    name: str
    kind: str  # latency | error_rate | throughput
    # latency:
    phase: str = "decide"
    threshold_ms: float = 250.0
    # error_rate (dotted paths into the stats tree):
    numerator: str = "failed_bindings"
    denominator: str = "total_scheduled"
    # throughput:
    counter: str = "total_scheduled"
    min_per_s: float = 1.0
    # shared:
    budget: float = 0.01  # allowed violation fraction (latency/error_rate)
    fast_burn_threshold: float | None = None  # kind-dependent default
    slow_burn_threshold: float | None = None

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"slo objective {self.name!r}: kind {self.kind!r} "
                f"not in {KINDS}"
            )
        if self.kind != "throughput" and self.budget <= 0:
            raise ValueError(
                f"slo objective {self.name!r}: budget must be > 0"
            )

    @property
    def fast_threshold(self) -> float:
        if self.fast_burn_threshold is not None:
            return self.fast_burn_threshold
        return 1.0 if self.kind == "throughput" else 14.4

    @property
    def slow_threshold(self) -> float:
        if self.slow_burn_threshold is not None:
            return self.slow_burn_threshold
        return 1.0 if self.kind == "throughput" else 6.0

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SloObjective":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"slo objective {d.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)} (known: {sorted(known)})"
            )
        if "name" not in d or "kind" not in d:
            raise ValueError("slo objective needs 'name' and 'kind'")
        return cls(**d)


def _resolve(stats: dict, dotted: str) -> float:
    """Dotted-path counter lookup; a missing path reads 0 (a replica that
    has not produced the stat yet must not crash evaluation)."""
    node: Any = stats
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return 0.0
        node = node[part]
    return float(node) if isinstance(node, (int, float)) else 0.0


def _violations_above(counts: list[int], threshold_ms: float) -> int:
    """Events whose bucket LOWER bound >= threshold — each is guaranteed
    to exceed the threshold (conservative; see module docstring)."""
    threshold_s = threshold_ms / 1000.0
    viol = 0
    for i, c in enumerate(counts):
        lower = 0.0 if i == 0 else BUCKET_BOUNDS_S[i - 1]
        if i == len(BUCKET_BOUNDS_S):  # overflow bucket
            lower = BUCKET_BOUNDS_S[-1]
        if lower >= threshold_s:
            viol += int(c)
    return viol


class SloEngine:
    """Multi-window burn-rate evaluation over a stats provider.

    Keeps a ring of timestamped stats snapshots; each `evaluate()` takes a
    fresh snapshot and derives per-objective fast/slow-window burns from
    the delta against the snapshot nearest each window's start. With a
    young ring the window degrades to actual coverage (reported as
    `window_covered_s`) rather than refusing to answer — a scheduler five
    minutes old still gets a fast-window verdict.
    """

    def __init__(
        self,
        objectives: list[SloObjective],
        stats_provider: Callable[[], dict],
        *,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.objectives = list(objectives)
        self.stats_provider = stats_provider
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._snaps: deque[tuple[float, dict]] = deque()
        self._tripped: set[str] = set()
        self._last_eval: dict[str, dict] = {}
        self.trip_counts: dict[str, int] = {o.name: 0 for o in self.objectives}
        self.evaluations = 0
        # rising-edge callbacks: fn(objective_name, detail_dict)
        self.on_trip: list[Callable[[str, dict], None]] = []
        # falling-edge callbacks (the burn dropped back under threshold):
        # fn(objective_name, detail_dict). The consumer that needs both
        # edges is the decision brownout (sched/client.py): on_trip
        # enters it, on_clear exits it — without the falling edge a
        # single burn would shed decisions forever.
        self.on_clear: list[Callable[[str, dict], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # lifecycle lock: start/stop are called from more than one owner
        # (MetricsServer.stop, cli shutdown paths, autoscale-driven
        # controller restarts) — without serialization a start racing a
        # stop could observe the dying thread's slot as free, clear the
        # stop event under it, and leak BOTH threads
        self._lifecycle = threading.Lock()

    # Stored-baseline resolution: _baseline only ever picks the snapshot
    # nearest a window cutoff, so the ring needs ~this many points per
    # window, not one per evaluate tick — without thinning, a sub-second
    # interval_s against the default 1h slow window accumulates tens of
    # thousands of full stats trees (each holding every phase histogram).
    POINTS_PER_WINDOW = 128

    def _thin(self, now: float) -> None:
        """Bound the snapshot ring (caller holds the lock): evict past the
        slow horizon (keeping one so a full-window baseline exists), then
        thin survivors to POINTS_PER_WINDOW resolution — fast-window
        granularity while young, slow-window granularity once older than
        the fast window. Baseline times stay exact (window_covered_s is
        computed from the snapshot actually used), only their spacing
        coarsens."""
        horizon = now - self.slow_window_s
        while len(self._snaps) > 2 and self._snaps[1][0] <= horizon:
            self._snaps.popleft()
        fast_edge = now - self.fast_window_s
        fast_r = self.fast_window_s / self.POINTS_PER_WINDOW
        slow_r = self.slow_window_s / self.POINTS_PER_WINDOW
        kept: list[tuple[float, dict]] = []
        for t, snap in self._snaps:
            if kept:
                spacing = slow_r if t <= fast_edge else fast_r
                if t - kept[-1][0] < spacing:
                    continue
            kept.append((t, snap))
        if len(kept) != len(self._snaps):
            self._snaps = deque(kept)

    # ----------------------------------------------------------- windows
    def _baseline(self, now: float, window_s: float) -> tuple[float, dict] | None:
        """Newest snapshot at least `window_s` old (else the oldest held —
        degraded coverage)."""
        cutoff = now - window_s
        best: tuple[float, dict] | None = None
        for t, snap in self._snaps:
            if t <= cutoff:
                best = (t, snap)
            else:
                break
        if best is None and self._snaps:
            best = self._snaps[0]
        return best

    def _burn(
        self, obj: SloObjective, base_t: float, base: dict,
        now: float, cur: dict,
    ) -> dict:
        covered = max(now - base_t, 1e-9)
        if obj.kind == "latency":
            dh = delta_hist(
                (base.get("phases") or {}).get(obj.phase),
                (cur.get("phases") or {}).get(obj.phase),
            )
            total = int(dh["count"]) if dh else 0
            viol = _violations_above(dh["counts"], obj.threshold_ms) if dh else 0
            frac = viol / total if total else 0.0
            return {
                "burn": frac / obj.budget,
                "violations": viol,
                "events": total,
                "window_covered_s": round(covered, 1),
            }
        if obj.kind == "error_rate":
            num = _resolve(cur, obj.numerator) - _resolve(base, obj.numerator)
            den = (
                _resolve(cur, obj.denominator)
                - _resolve(base, obj.denominator)
            )
            frac = (num / den) if den > 0 else 0.0
            return {
                "burn": max(frac, 0.0) / obj.budget,
                "violations": int(max(num, 0)),
                "events": int(max(den, 0)),
                "window_covered_s": round(covered, 1),
            }
        # throughput floor: burn = floor / achieved rate (>1 = violating).
        # Zero traffic against a floor is a full-rate burn, not a crash.
        delta = _resolve(cur, obj.counter) - _resolve(base, obj.counter)
        rate = max(delta, 0.0) / covered
        burn = (obj.min_per_s / rate) if rate > 0 else float(10 * obj.fast_threshold or 10.0)
        return {
            "burn": burn,
            "rate_per_s": round(rate, 3),
            "window_covered_s": round(covered, 1),
        }

    # ---------------------------------------------------------- evaluate
    def evaluate(self) -> dict[str, dict]:
        """Take a snapshot and re-derive every objective's burn state.
        Returns {objective: {fast, slow, tripped, ...}}; fires on_trip
        hooks on rising edges."""
        now = self._clock()
        cur = self.stats_provider()
        rising: list[tuple[str, dict]] = []
        falling: list[tuple[str, dict]] = []
        with self._lock:
            self.evaluations += 1
            results: dict[str, dict] = {}
            fast_base = self._baseline(now, self.fast_window_s)
            slow_base = self._baseline(now, self.slow_window_s)
            for obj in self.objectives:
                fast = (
                    self._burn(obj, fast_base[0], fast_base[1], now, cur)
                    if fast_base is not None else None
                )
                slow = (
                    self._burn(obj, slow_base[0], slow_base[1], now, cur)
                    if slow_base is not None else None
                )
                tripped = bool(
                    fast is not None and slow is not None
                    and fast["burn"] > obj.fast_threshold
                    and slow["burn"] > obj.slow_threshold
                )
                detail = {
                    "kind": obj.kind,
                    "fast": fast,
                    "slow": slow,
                    "fast_threshold": obj.fast_threshold,
                    "slow_threshold": obj.slow_threshold,
                    "tripped": tripped,
                }
                results[obj.name] = detail
                if tripped and obj.name not in self._tripped:
                    self._tripped.add(obj.name)
                    self.trip_counts[obj.name] += 1
                    rising.append((obj.name, detail))
                elif not tripped and obj.name in self._tripped:
                    self._tripped.discard(obj.name)
                    falling.append((obj.name, detail))
            self._last_eval = results
            self._snaps.append((now, cur))
            self._thin(now)
        for name, detail in rising:
            logger.warning(
                "SLO TRIP %s: fast burn %.2fx (>%.1fx), slow burn %.2fx "
                "(>%.1fx)", name,
                detail["fast"]["burn"], detail["fast_threshold"],
                detail["slow"]["burn"], detail["slow_threshold"],
            )
            for hook in list(self.on_trip):
                try:
                    hook(name, detail)
                except Exception:
                    logger.exception("slo on_trip hook failed for %s", name)
        for name, detail in falling:
            logger.info("SLO trip cleared: %s", name)
            for hook in list(self.on_clear):
                try:
                    hook(name, detail)
                except Exception:
                    logger.exception("slo on_clear hook failed for %s", name)
        return results

    def tripped(self) -> list[str]:
        """Names of objectives currently in trip (as of the last
        evaluate()) — the canary burn-in's input."""
        with self._lock:
            return sorted(self._tripped)

    # ------------------------------------------------------------ exports
    def snapshot(self) -> dict:
        """The /debug/slo payload."""
        with self._lock:
            return {
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "evaluations": self.evaluations,
                "snapshots_held": len(self._snaps),
                "trip_counts": dict(self.trip_counts),
                "tripped": sorted(self._tripped),
                "objectives": dict(self._last_eval),
            }

    def gauges(self) -> dict[str, Any]:
        """Flat numeric view for /metrics (llm_scheduler_slo_* gauges)."""
        with self._lock:
            out: dict[str, Any] = {"evaluations": self.evaluations}
            for name, detail in self._last_eval.items():
                if detail.get("fast"):
                    out[f"{name}_fast_burn"] = round(
                        detail["fast"]["burn"], 4
                    )
                if detail.get("slow"):
                    out[f"{name}_slow_burn"] = round(
                        detail["slow"]["burn"], 4
                    )
                out[f"{name}_tripped"] = bool(detail["tripped"])
                out[f"{name}_trips_total"] = self.trip_counts.get(name, 0)
        return out

    # ---------------------------------------------------------- lifecycle
    def start(self, interval_s: float = 10.0) -> None:
        """Background evaluation ticker (same restartable discipline as
        EngineSampler: stop() sets the event, start() clears it).
        IDEMPOTENT under repeated controller restarts: a double start is
        a no-op while the ticker lives (never a second thread), and a
        start racing a stop waits for the old thread to be joined
        before clearing the stop event (clearing it early would revive
        the dying thread alongside the new one)."""
        with self._lifecycle:
            if self._thread is not None:
                return
            self._stop.clear()
            interval = max(0.05, float(interval_s))

            def run() -> None:
                while not self._stop.wait(interval):
                    try:
                        self.evaluate()
                    except Exception:
                        logger.exception("slo evaluation failed")

            self._thread = threading.Thread(
                target=run, daemon=True, name="slo-engine"
            )
            self._thread.start()

    def stop(self) -> None:
        """Idempotent: the first caller joins the ticker exactly once
        (MetricsServer.stop and the owner's own shutdown path may both
        call this); later callers find no thread and return."""
        with self._lifecycle:
            self._stop.set()
            thread = self._thread
            self._thread = None
            if thread is not None:
                thread.join(timeout=5)


def from_config(
    slo_cfg: dict[str, Any], stats_provider: Callable[[], dict],
    clock: Callable[[], float] = time.monotonic,
) -> SloEngine | None:
    """Build an SloEngine from the config `slo` section (None when
    disabled or no objectives are declared)."""
    if not slo_cfg or not slo_cfg.get("enabled"):
        return None
    objectives = [
        SloObjective.from_dict(d) for d in slo_cfg.get("objectives") or []
    ]
    if not objectives:
        return None
    return SloEngine(
        objectives,
        stats_provider,
        fast_window_s=float(slo_cfg.get("fast_window_s", 300.0)),
        slow_window_s=float(slo_cfg.get("slow_window_s", 3600.0)),
        clock=clock,
    )
