"""Trace-id'd span tracing + the decision flight recorder.

The reference's only instrumentation is a running average of remote-API
wall time (reference scheduler.py:435-441); through round 7 our own rebuild
exported only point-in-time gauges and count/total/max phase aggregates.
Neither can answer "why was THIS pod's placement slow?" — the per-decision
question every tail-latency investigation starts with (SARATHI and
SwiftSpec both report p99 attribution across the prefill/decode boundary,
never averages). This module adds exactly that:

- **Spans**: named, trace-id'd wall-time intervals forming a tree. One
  trace per scheduling decision (sched/loop.py opens it per watch event);
  children cover snapshot / decide (backend attempts, admission wait,
  prefill, decode) / bind. Propagation is a `contextvars.ContextVar`, so
  the asyncio pipeline carries the ambient trace with zero plumbing;
  thread-crossing hops (the engine worker in engine/local.py) capture an
  explicit `SpanContext` and attach retroactive spans at harvest.
- **Cross-process stitching**: `wire_context()` serializes (trace_id,
  span_id) into a replica RPC frame; the worker opens a remote-rooted
  trace, and its serialized spans ride back in the response for
  `merge_remote_spans` to graft into the coordinator's trace
  (sched/replica.py). Span times are wall-clock (time.time) + perf_counter
  durations, so stitched trees stay meaningful across processes.
- **Flight recorder**: a bounded ring of the last N COMPLETE decision
  traces (span tree + decision metadata: source, fallback reason, cache
  key/generation, token counts), queryable via /debug/decisions and
  /debug/trace/<id> on MetricsServer and `cli trace` (list/show/tail/
  export — JSONL, replayable alongside sim traces).

Cost discipline: tracing is ON by default but every span is a dataclass
append + two clock reads; with tracing disabled (`configure(enabled=False)`
or `observability.tracing: false`) `span()` is a shared no-op context
manager and `start_trace` yields None — the knob `bench.py --preset
obs-overhead` A/Bs (< 2% of decision p50, SCALING.md).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Iterator

_id_counter = itertools.count(1)
_ID_LOCK = threading.Lock()
_PROC_TAG = f"{time.time_ns() & 0xFFFFFF:06x}"


def _new_id() -> str:
    # monotonic counter + per-process tag: unique, cheap (no os.urandom on
    # the per-decision hot path), and stable for tests to compare
    with _ID_LOCK:
        n = next(_id_counter)
    return f"{_PROC_TAG}-{n:x}"


@dataclasses.dataclass
class Span:
    """One named wall-time interval in a trace tree.

    `start_unix` is wall-clock (time.time) so spans stitched across
    processes stay ordered; `dur_ms` comes from perf_counter deltas so
    durations keep sub-ms resolution. `dur_ms` is None while the span is
    open."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_unix: float
    dur_ms: float | None = None
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "ok"

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix": self.start_unix,
            "dur_ms": self.dur_ms,
            # copy, never alias: span() callers mutate the live attrs dict
            # mid-block (engine wave/spec counters), while a recorded ring
            # entry may be serialized by a /debug handler thread at any
            # time — an aliased dict is the same changed-size-during-
            # iteration race set_meta exists to prevent for trace meta
            "attrs": dict(self.attrs),
            "status": self.status,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Span":
        return cls(
            name=d["name"],
            trace_id=d["trace_id"],
            span_id=d["span_id"],
            parent_id=d.get("parent_id"),
            start_unix=float(d.get("start_unix", 0.0)),
            dur_ms=d.get("dur_ms"),
            attrs=dict(d.get("attrs") or {}),
            status=d.get("status", "ok"),
        )


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """Wire/thread-portable handle: enough to parent new spans under an
    existing trace from another thread or process."""

    trace_id: str
    span_id: str


class Trace:
    """One decision's span collection. Spans append under a lock — the
    engine worker and the asyncio loop both write to the same trace."""

    __slots__ = ("trace_id", "root", "spans", "meta", "_lock", "_recorder")

    def __init__(self, name: str, trace_id: str | None = None,
                 parent_id: str | None = None, **attrs: Any) -> None:
        self.trace_id = trace_id or _new_id()
        self._lock = threading.Lock()
        self.spans: list[Span] = []
        # decision metadata stamped by the pipeline as it learns things
        # (source, fallback reason, cache key, token counts, ...)
        self.meta: dict[str, Any] = {}
        # set by FlightRecorder.record: spans attached AFTER the root
        # closed (a timed-out decision whose wave harvests later) re-
        # publish the serialized ring entry instead of being silently lost
        self._recorder = None
        self.root = Span(
            name=name,
            trace_id=self.trace_id,
            span_id=_new_id(),
            parent_id=parent_id,
            start_unix=time.time(),  # graftlint: ok[raw-clock] — spans are wall-ANCHORED by design so trees stitch across processes
            attrs=dict(attrs),
        )
        self.spans.append(self.root)

    def add_span(
        self,
        name: str,
        start_unix: float,
        dur_ms: float,
        parent_id: str | None = None,
        status: str = "ok",
        publish: bool = True,
        **attrs: Any,
    ) -> Span:
        """Attach a RETROACTIVE span (interval already over) — the shape
        thread-crossing producers need: the engine worker learns a wave's
        timings only at harvest, long after the interval started.

        `publish=False` defers the ring re-publication for batch
        producers — call flush() once after the last span instead of
        paying a full reserialization per span."""
        sp = Span(
            name=name,
            trace_id=self.trace_id,
            span_id=_new_id(),
            parent_id=parent_id or self.root.span_id,
            start_unix=start_unix,
            dur_ms=float(dur_ms),
            attrs=dict(attrs),
            status=status,
        )
        with self._lock:
            self.spans.append(sp)
        if publish:
            self.flush()
        return sp

    def set_meta(self, **meta: Any) -> None:
        """Stamp decision metadata under the trace lock.

        Stamps arrive from the pipeline (sched/loop, sched/client, cli)
        while a metrics-server handler thread may be serializing this
        very trace for /debug/decisions — an unguarded `self.meta[...] =`
        during to_dict's `dict(self.meta)` copy is a "dictionary changed
        size during iteration" RuntimeError that kills the scrape.
        (Found by this PR's concurrency sweep; direct `trace.meta[...]`
        writes outside this module are the hazard.)"""
        with self._lock:
            self.meta.update(meta)

    def flush(self) -> None:
        """Re-publish this trace's ring entry if it was already recorded
        (root closed before this producer caught up — e.g. the decision
        timed out and fell back while its wave was still on device), so
        /debug/trace shows the engine attribution for exactly the tail
        decisions the recorder exists to explain. No-op pre-record."""
        recorder = self._recorder
        if recorder is not None:
            recorder.refresh(self)

    def merge_remote_spans(self, spans: list[dict]) -> int:
        """Graft spans serialized by a remote process (sched/replica.py
        response frames) into this trace. Only spans carrying this trace's
        id are accepted — a desynced frame must not pollute the tree."""
        merged = 0
        for d in spans:
            try:
                sp = Span.from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
            if sp.trace_id != self.trace_id:
                continue
            with self._lock:
                self.spans.append(sp)
            merged += 1
        if merged:
            self.flush()
        return merged

    def to_dict(self) -> dict[str, Any]:
        with self._lock:
            # meta copied under the SAME lock set_meta writes under — a
            # concurrent stamp must not blow up this serialization
            spans = [s.to_dict() for s in self.spans]
            meta = dict(self.meta)
        return {
            "trace_id": self.trace_id,
            "name": self.root.name,
            "start_unix": self.root.start_unix,
            "dur_ms": self.root.dur_ms,
            "status": self.root.status,
            "meta": meta,
            "spans": spans,
        }

    def span_tree(self) -> dict[str, Any]:
        """The span tree (children nested), for humans and tests."""
        with self._lock:
            dicts = [s.to_dict() for s in self.spans]
        return build_span_tree(dicts)


def build_span_tree(span_dicts: list[dict]) -> dict[str, Any]:
    """Nest serialized spans by parent_id (shared by Trace.span_tree and
    `cli trace show`, which only has the wire form). Spans whose parent is
    not in the set (a remote root whose parent lived on the other side of
    the wire before merging, or an orphan) hang off the local root."""
    ids = {s["span_id"] for s in span_dicts}
    by_parent: dict[str | None, list[dict]] = {}
    for s in span_dicts:
        parent = s.get("parent_id") if s.get("parent_id") in ids else None
        by_parent.setdefault(parent, []).append(s)

    def node(s: dict) -> dict[str, Any]:
        kids = sorted(
            by_parent.get(s["span_id"], []),
            key=lambda c: c.get("start_unix", 0.0),
        )
        return {**s, "children": [node(k) for k in kids]}

    roots = sorted(
        by_parent.get(None, []), key=lambda s: s.get("start_unix", 0.0)
    )
    # single decision root in the normal case; keep the forest shape for
    # robustness against multiple orphans
    return node(roots[0]) if len(roots) == 1 else {
        "name": "forest", "children": [node(r) for r in roots],
    }


# ------------------------------------------------------------ ambient state
_current: contextvars.ContextVar[tuple[Trace, Span] | None] = (
    contextvars.ContextVar("obs_span", default=None)
)


class _NullCtx:
    """Shared no-op context manager: the disabled/traceless fast path must
    not allocate per call."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL = _NullCtx()


def budget_slice(
    matched: list[dict],
    since_seq: int = 0,
    max_traces: int | None = None,
    max_bytes: int | None = None,
) -> tuple[list[dict], int, bool]:
    """Apply trace-count + byte caps to cursor-ordered entries (each
    carrying a `seq`). Returns (kept, next_cursor, truncated) — the one
    budget loop behind FlightRecorder.export_slices (/debug/export,
    telemetry_pull) and /debug/decisions' summary pagination. At least
    one entry is always kept when any matched, so a single oversized
    entry cannot wedge the cursor."""
    entries: list[dict] = []
    next_cursor = since_seq
    spent = 0
    truncated = False
    for e in matched:
        if max_traces is not None and len(entries) >= max_traces:
            truncated = True
            break
        size = len(json.dumps(e, separators=(",", ":")))
        if max_bytes is not None and entries and spent + size > max_bytes:
            truncated = True
            break
        entries.append(e)
        spent += size
        next_cursor = e["seq"]
    return entries, next_cursor, truncated


class FlightRecorder:
    """Bounded ring of the last N complete decision traces.

    `seq` is a monotonically increasing completion counter so `cli trace
    tail` can poll for "traces since X" without re-reading the ring."""

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        # deque(maxlen): O(1) eviction — record() runs synchronously at
        # root-span close on the scheduler loop, so a full ring must not
        # pay a per-decision element shift
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self.seq = 0

    def record(self, trace: Trace) -> None:
        # _recorder is set BEFORE serializing: a producer attaching a span
        # concurrently with the root close then either lands in the
        # serialization, or sees _recorder and refreshes. Its refresh can
        # still no-op if it runs before the insert below — the post-insert
        # drift check closes that window.
        trace._recorder = self
        entry = trace.to_dict()
        with self._lock:
            self.seq += 1
            entry["seq"] = self.seq
            self._ring.append(entry)
        with trace._lock:
            drifted = len(trace.spans) != len(entry["spans"])
        if drifted:
            self.refresh(trace)

    def refresh(self, trace: Trace) -> None:
        """Replace this trace's ring entry with a fresh serialization
        (same seq). Rare path — only spans attached after the root
        closed; a no-op once the ring evicted the entry."""
        entry = trace.to_dict()
        with self._lock:
            for i, old in enumerate(self._ring):
                if old["trace_id"] == trace.trace_id:
                    entry["seq"] = old["seq"]
                    self._ring[i] = entry
                    return

    def list(
        self, n: int | None = 50, since_seq: int = 0,
    ) -> list[dict]:
        """Newest-last summaries (cheap fields only — the list endpoint
        must stay small at ring capacity). `n` keeps the NEWEST n (the
        recent-traces view); pass None for every match past the cursor —
        what a forward-pagination walk needs, since a newest-n cut would
        silently skip older entries without marking truncation."""
        with self._lock:
            entries = [e for e in self._ring if e["seq"] > since_seq]
        if n is not None:
            entries = entries[-n:]
        return [
            {
                "seq": e["seq"],
                "trace_id": e["trace_id"],
                "name": e["name"],
                "start_unix": e["start_unix"],
                "dur_ms": e["dur_ms"],
                "status": e["status"],
                "n_spans": len(e["spans"]),
                "meta": e["meta"],
            }
            for e in entries
        ]

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            for e in reversed(self._ring):
                if e["trace_id"] == trace_id:
                    return e
        return None

    def export_jsonl(self) -> str:
        """One canonical-JSON trace per line — the same file shape sim
        traces use, so recorded decisions replay alongside them."""
        with self._lock:
            entries = list(self._ring)
        return "".join(
            json.dumps(e, sort_keys=True, separators=(",", ":")) + "\n"
            for e in entries
        )

    def export_slices(
        self,
        since_seq: int = 0,
        max_traces: int | None = None,
        max_bytes: int | None = None,
    ) -> tuple[list[dict], int, bool]:
        """Since-cursor trace slices with a HARD response-size cap.

        Returns (entries, next_cursor, truncated). `next_cursor` is the
        last included entry's seq (or `since_seq` when nothing fit) — pass
        it back as `since_seq` to resume; `truncated` is True when more
        entries matched the cursor than the caps allowed. This is the
        shape a 16-replica `telemetry_pull` fans in: without the cap one
        frame could ship the whole ring per replica per scrape
        (observability/fleetview.py; /debug/export routes through it too,
        and /debug/decisions applies the same `budget_slice` to its
        summaries). The byte budget counts each entry's canonical-JSON
        size; at least one entry is always shipped when any matches, so a
        single oversized trace cannot wedge the cursor."""
        with self._lock:
            matched = [e for e in self._ring if e["seq"] > since_seq]
        return budget_slice(
            matched, since_seq=since_seq,
            max_traces=max_traces, max_bytes=max_bytes,
        )

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"recorded": self.seq, "held": len(self._ring),
                    "capacity": self.capacity}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# Process-global defaults — components grab tracing without plumbing, the
# same idiom as observability.trace.recorder.
flight = FlightRecorder()
_enabled = True


def configure(enabled: bool | None = None, capacity: int | None = None) -> None:
    """Apply the `observability.*` config block (cli wiring)."""
    global _enabled, flight
    if enabled is not None:
        _enabled = bool(enabled)
    if capacity is not None and capacity != flight.capacity:
        flight = FlightRecorder(capacity)


def enabled() -> bool:
    return _enabled


def start_trace(
    name: str,
    recorder: FlightRecorder | None = None,
    trace_id: str | None = None,
    parent_id: str | None = None,
    start_unix: float | None = None,
    start_perf: float | None = None,
    **attrs: Any,
):
    """Open a new trace and make it ambient for the block. On exit the
    root span closes and the trace publishes to `recorder` (default: the
    global flight recorder). Yields None (via the shared no-op context
    manager — no per-call allocation) when tracing is disabled.

    `start_unix`/`start_perf` BACKDATE the root: the fast/follower paths
    open their trace only after the decision already resolved, and without
    backdating the root would cover just the bind — the list view's
    duration column would filter out exactly the slow decisions it exists
    to surface."""
    if not _enabled:
        return _NULL
    return _start_trace_cm(
        name, recorder, trace_id, parent_id, start_unix, start_perf, attrs
    )


@contextlib.contextmanager
def _start_trace_cm(
    name, recorder, trace_id, parent_id, start_unix, start_perf, attrs
) -> Iterator[Trace]:
    trace = Trace(name, trace_id=trace_id, parent_id=parent_id, **attrs)
    if start_unix is not None:
        trace.root.start_unix = start_unix
    t0 = start_perf if start_perf is not None else time.perf_counter()
    token = _current.set((trace, trace.root))
    try:
        yield trace
    except BaseException:
        trace.root.status = "error"
        raise
    finally:
        _current.reset(token)
        trace.root.dur_ms = (time.perf_counter() - t0) * 1000.0
        (recorder if recorder is not None else flight).record(trace)


def span(name: str, **attrs: Any):
    """Child span under the ambient trace; without one (or with tracing
    disabled) returns the SHARED no-op context manager — the hot path
    allocates nothing. The caller may mutate the yielded span's attrs
    mid-block."""
    cur = _current.get() if _enabled else None
    if cur is None:
        return _NULL
    return _span_cm(name, cur, attrs)


@contextlib.contextmanager
def _span_cm(
    name: str, cur: tuple[Trace, Span], attrs: dict
) -> Iterator[Span]:
    trace, parent = cur
    sp = Span(
        name=name,
        trace_id=trace.trace_id,
        span_id=_new_id(),
        parent_id=parent.span_id,
        start_unix=time.time(),  # graftlint: ok[raw-clock] — spans are wall-ANCHORED by design so trees stitch across processes
        attrs=attrs,
    )
    with trace._lock:
        trace.spans.append(sp)
    t0 = time.perf_counter()
    token = _current.set((trace, sp))
    try:
        yield sp
    except BaseException:
        sp.status = "error"
        raise
    finally:
        _current.reset(token)
        sp.dur_ms = (time.perf_counter() - t0) * 1000.0


def current_trace() -> Trace | None:
    cur = _current.get()
    return cur[0] if cur is not None else None


def context() -> SpanContext | None:
    """Portable handle to the ambient span (for thread-crossing hops)."""
    cur = _current.get() if _enabled else None
    if cur is None:
        return None
    trace, sp = cur
    return SpanContext(trace_id=trace.trace_id, span_id=sp.span_id)


def capture() -> tuple[Trace, SpanContext] | None:
    """(trace handle, span context) for producers that will attach
    retroactive spans from another thread (engine/local.py work items)."""
    cur = _current.get() if _enabled else None
    if cur is None:
        return None
    trace, sp = cur
    return trace, SpanContext(trace_id=trace.trace_id, span_id=sp.span_id)


def wire_context() -> dict[str, str] | None:
    """The cross-process form: a small dict for an RPC frame
    (sched/replica.py adds it as the "trace" field)."""
    ctx = context()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
