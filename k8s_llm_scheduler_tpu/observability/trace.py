"""Per-phase timing with histogram buckets, plus optional JAX profiler capture.

The reference's only instrumentation is a running average of remote-API wall
time (reference scheduler.py:435-441; SURVEY §5 tracing: "none"). Here every
scheduling decision can be broken into phases —
watch -> snapshot -> prompt -> prefill -> decode -> bind — with a low-overhead
recorder, plus a context manager around `jax.profiler` for device traces.

Since the observability round the recorder keeps fixed LOG-SPACED buckets per
phase, not just count/total/max: averages hide exactly the tail the sim arena
(per-wave latency attribution) and the canary burn-in (regression trips)
decide on, and "bind p99 under burst" is unanswerable from a mean. The bucket
bounds are shared process-wide (`BUCKET_BOUNDS_S`), so two snapshots of the
same recorder subtract bucket-by-bucket — which is how per-wave and burn-in
WINDOW percentiles are derived (`delta_hist`, `hist_percentiles`).
render_prometheus (observability/metrics.py) recognizes the embedded
histogram dicts and exports genuine Prometheus `histogram` families
(`_bucket`/`_sum`/`_count`) next to derived p50/p95/p99 gauges.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from typing import Any, Iterator

logger = logging.getLogger(__name__)

# Fixed log-spaced bucket bounds in SECONDS: 100 us doubling up to ~420 s.
# 23 buckets cover a 4-decade dynamic range (a 0.2 ms cache-hit bind to a
# multi-minute cold-compile decide) at <=2x resolution — fixed so every
# snapshot of every recorder subtracts bucket-by-bucket, and small enough
# that a snapshot copy is ~a hundred ints per phase.
BUCKET_BOUNDS_S: tuple[float, ...] = tuple(1e-4 * 2**i for i in range(23))

# Marker key for embedded histogram dicts: metrics._flatten skips them and
# render_prometheus turns them into `histogram` exposition families.
HIST_KEY = "_hist"


def hist_percentiles(
    counts: list[int], quantiles: tuple[float, ...] = (0.5, 0.95, 0.99)
) -> list[float]:
    """Percentile estimates (in ms) from NON-cumulative bucket counts.

    Reports the bucket's upper bound (the overflow bucket reports the last
    finite bound x2) — a deliberately conservative, monotone estimator: a
    derived p99 gauge must never understate the tail it summarizes."""
    total = sum(counts)
    out: list[float] = []
    bounds_ms = [b * 1000.0 for b in BUCKET_BOUNDS_S]
    overflow_ms = bounds_ms[-1] * 2.0
    for q in quantiles:
        if total <= 0:
            out.append(0.0)
            continue
        rank = q * total
        acc = 0
        value = overflow_ms
        for i, c in enumerate(counts):
            acc += c
            if acc >= rank:
                value = bounds_ms[i] if i < len(bounds_ms) else overflow_ms
                break
        out.append(value)
    return out


def delta_hist(before: dict | None, after: dict | None) -> dict | None:
    """Bucket-wise difference of two phase snapshots' histogram dicts —
    the window percentile instrument (arena per-wave attribution, canary
    burn-in): subtracting two cumulative snapshots yields the histogram of
    ONLY the events between them."""
    a = (after or {}).get(HIST_KEY)
    if a is None:
        return None
    b = (before or {}).get(HIST_KEY)
    if b is None:
        counts = list(a["counts"])
        return {
            "counts": counts,
            "sum_s": a["sum_s"],
            "count": a["count"],
        }
    counts = [max(x - y, 0) for x, y in zip(a["counts"], b["counts"])]
    return {
        "counts": counts,
        "sum_s": max(a["sum_s"] - b["sum_s"], 0.0),
        "count": max(a["count"] - b["count"], 0),
    }


class PhaseRecorder:
    """Thread-safe accumulator of per-phase durations.

    Per phase: count / total / max plus fixed log-spaced bucket counts
    (BUCKET_BOUNDS_S + one overflow bucket). The record path is one lock
    acquisition, one bisect-free bucket walk (bounds double, so
    `bit_length` indexes in O(1)), and three dict writes."""

    _N_BUCKETS = len(BUCKET_BOUNDS_S) + 1  # + overflow

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count: dict[str, int] = {}
        self._total: dict[str, float] = {}
        self._max: dict[str, float] = {}
        self._buckets: dict[str, list[int]] = {}

    @staticmethod
    def _bucket_index(seconds: float) -> int:
        # bounds are 1e-4 * 2**i: the smallest i with seconds <= bound is
        # ceil(log2(seconds/1e-4)), computed via bit_length — no per-record
        # list scan. Float rounding at an exact boundary may land one
        # bucket up, which stays a valid (conservative) histogram.
        if seconds <= BUCKET_BOUNDS_S[0]:
            return 0
        ratio = math.ceil(seconds / 1e-4)
        return min((ratio - 1).bit_length(), len(BUCKET_BOUNDS_S))

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - start)

    def record(self, name: str, seconds: float) -> None:
        idx = self._bucket_index(seconds)
        with self._lock:
            if name not in self._count:
                self._count[name] = 0
                self._total[name] = 0.0
                self._max[name] = 0.0
                self._buckets[name] = [0] * self._N_BUCKETS
            self._count[name] += 1
            self._total[name] += seconds
            if seconds > self._max[name]:
                self._max[name] = seconds
            self._buckets[name][idx] += 1

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """Consistent per-phase stats. State is COPIED under one lock
        acquisition and all derivation happens outside it: building the
        output dict entry-by-entry while racing record()/reset() is what
        once made `total/count` a divide-by-zero hazard (a reset between
        the total read and the count read), and long snapshot math must
        not hold the hot path's lock either way."""
        with self._lock:
            counts = dict(self._count)
            totals = dict(self._total)
            maxes = dict(self._max)
            buckets = {k: list(v) for k, v in self._buckets.items()}
        out: dict[str, dict[str, Any]] = {}
        for name, n in counts.items():
            total = totals.get(name, 0.0)
            bkt = buckets.get(name, [0] * self._N_BUCKETS)
            p50, p95, p99 = hist_percentiles(bkt)
            out[name] = {
                "count": n,
                "total_ms": total * 1000.0,
                "avg_ms": (total / max(n, 1)) * 1000.0,
                "max_ms": maxes.get(name, 0.0) * 1000.0,
                "p50_ms": p50,
                "p95_ms": p95,
                "p99_ms": p99,
                HIST_KEY: {
                    "counts": bkt,  # non-cumulative, overflow last
                    "sum_s": total,
                    "count": n,
                },
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._count.clear()
            self._total.clear()
            self._max.clear()
            self._buckets.clear()


# Global default recorder — components grab phases without plumbing.
recorder = PhaseRecorder()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (TensorBoard format) around a block.

    stop_trace is guarded: a failed capture teardown (profiler backend
    died, disk full) must never MASK the block's own exception — the
    original error is what the operator needs."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            logger.exception("device trace capture failed to stop cleanly")
