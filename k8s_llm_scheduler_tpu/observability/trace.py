"""Per-phase timing and optional JAX profiler capture.

The reference's only instrumentation is a running average of remote-API wall
time (reference scheduler.py:435-441; SURVEY §5 tracing: "none"). Here every
scheduling decision can be broken into phases —
watch -> snapshot -> prompt -> prefill -> decode -> bind — with a low-overhead
recorder, plus a context manager around `jax.profiler` for device traces.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Iterator


class PhaseRecorder:
    """Thread-safe accumulator of per-phase durations (count/total/max)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count: dict[str, int] = defaultdict(int)
        self._total: dict[str, float] = defaultdict(float)
        self._max: dict[str, float] = defaultdict(float)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self._count[name] += 1
                self._total[name] += elapsed
                self._max[name] = max(self._max[name], elapsed)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._count[name] += 1
            self._total[name] += seconds
            self._max[name] = max(self._max[name], seconds)

    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": self._count[name],
                    "total_ms": self._total[name] * 1000.0,
                    "avg_ms": (self._total[name] / self._count[name]) * 1000.0,
                    "max_ms": self._max[name] * 1000.0,
                }
                for name in self._count
            }

    def reset(self) -> None:
        with self._lock:
            self._count.clear()
            self._total.clear()
            self._max.clear()


# Global default recorder — components grab phases without plumbing.
recorder = PhaseRecorder()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (TensorBoard format) around a block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
