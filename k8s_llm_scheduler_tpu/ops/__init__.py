"""Attention and norm ops: XLA reference paths + Pallas TPU kernels."""
