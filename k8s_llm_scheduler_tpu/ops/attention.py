"""Attention ops for prefill and paged decode.

These are the XLA-compiled reference paths; ops/pallas_paged_attention.py
provides the hand-tiled TPU decode kernel behind the same signature. Both
paths are jit-compatible: static shapes, no Python control flow on traced
values (everything masks instead of branching).

Replaces the remote attention the reference rents from the HF-hosted 70B
(reference scheduler.py:425-433) with in-tree compute.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative mask value; avoids NaN from -inf * 0


def causal_prefill_attention(
    q: jax.Array,  # [B, S, n_heads, head_dim]
    k: jax.Array,  # [B, S, n_kv_heads, head_dim]
    v: jax.Array,  # [B, S, n_kv_heads, head_dim]
    seq_lens: jax.Array,  # [B] valid lengths (padding beyond)
) -> jax.Array:
    """Causal self-attention over a (padded) prompt chunk, GQA-aware.

    One fused einsum chain — XLA tiles this well onto the MXU; bf16 inputs,
    f32 softmax accumulation.
    """
    B, S, n_heads, head_dim = q.shape
    n_kv = k.shape[2]
    q_per_kv = n_heads // n_kv

    # Group heads: [B, S, n_kv, q_per_kv, hd]
    qg = q.reshape(B, S, n_kv, q_per_kv, head_dim)
    scale = head_dim**-0.5
    logits = jnp.einsum(
        "bqkgh,bskh->bkgqs", qg.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )  # [B, n_kv, q_per_kv, S_q, S_kv]

    pos = jnp.arange(S)
    causal = pos[:, None] >= pos[None, :]  # [S_q, S_kv]
    valid = pos[None, :] < seq_lens[:, None]  # [B, S_kv]
    mask = causal[None, None, None, :, :] & valid[:, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskh->bqkgh", weights, v.astype(jnp.float32))
    return out.reshape(B, S, n_heads, head_dim).astype(q.dtype)


def gather_pages(
    cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    page_table: jax.Array,  # [B, max_pages]
) -> jax.Array:
    """Gather each sequence's pages into a contiguous view
    [B, max_pages*page_size, n_kv, head_dim]."""
    gathered = cache[page_table]  # [B, max_pages, page_size, n_kv, hd]
    B, P, psize, n_kv, hd = gathered.shape
    return gathered.reshape(B, P * psize, n_kv, hd)


def paged_decode_attention(
    q: jax.Array,  # [B, n_heads, head_dim] — one new token per sequence
    k_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    v_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    page_table: jax.Array,  # [B, max_pages] page ids per sequence
    seq_lens: jax.Array,  # [B] length INCLUDING the new token
) -> jax.Array:
    """One decode step of attention against the paged KV cache.

    The new token's K/V must already be scattered into the cache (the model
    layer does that before calling). XLA path: gather pages then masked
    attention. The Pallas kernel version streams pages without
    materializing the gather.
    """
    B, n_heads, head_dim = q.shape
    n_kv = k_cache.shape[2]
    q_per_kv = n_heads // n_kv

    k = gather_pages(k_cache, page_table)  # [B, L, n_kv, hd]
    v = gather_pages(v_cache, page_table)
    L = k.shape[1]

    qg = q.reshape(B, n_kv, q_per_kv, head_dim)
    scale = head_dim**-0.5
    logits = jnp.einsum(
        "bkgh,blkh->bkgl", qg.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )  # [B, n_kv, q_per_kv, L]

    valid = jnp.arange(L)[None, :] < seq_lens[:, None]  # [B, L]
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgl,blkh->bkgh", weights, v.astype(jnp.float32))
    return out.reshape(B, n_heads, head_dim).astype(q.dtype)
