"""Attention ops for prefill and paged decode.

These are the XLA-compiled reference paths; ops/pallas_paged_attention.py
provides the hand-tiled TPU decode kernel behind the same signature. Both
paths are jit-compatible: static shapes, no Python control flow on traced
values (everything masks instead of branching).

Replaces the remote attention the reference rents from the HF-hosted 70B
(reference scheduler.py:425-433) with in-tree compute.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

NEG_INF = -1e30  # large-negative mask value; avoids NaN from -inf * 0

# Default shared-prefix attention implementation: "auto" picks the Pallas
# flash kernel (ops/pallas_prefix_attention.py) on TPU when the shapes meet
# its tiling constraints, else the XLA einsum path. "xla" forces the einsum
# path; "pallas" forces the kernel (interpret-mode on CPU — parity tests).
# On a multi-device mesh the engine passes a ShardedAttnImpl instead of a
# string: GSPMD cannot partition a pallas_call, so the kernel is wrapped in
# shard_map over the tp-sharded kv-head axis (per-shard it is
# embarrassingly parallel — no collectives).
PREFIX_ATTN_IMPL = "auto"


@dataclasses.dataclass(frozen=True)
class ShardedAttnImpl:
    """Attention-impl choice for a tp-sharded mesh.

    `kind` is the same auto/xla/pallas preference as the string form; the
    mesh+axis let the dispatch wrap Pallas kernels in shard_map over the
    kv-head axis instead of falling back to XLA (the round-2 behavior,
    which cost the 70B tp=8 serving path both flash kernels)."""

    mesh: Mesh
    axis: str = "tp"
    kind: str = "auto"


def _resolve_impl(impl) -> tuple[str, Mesh | None, str | None, int]:
    """Normalize str | ShardedAttnImpl | None -> (kind, mesh, axis, shards)."""
    if impl is None:
        impl = PREFIX_ATTN_IMPL
    if isinstance(impl, ShardedAttnImpl):
        shards = impl.mesh.shape.get(impl.axis, 1)
        kind = impl.kind or PREFIX_ATTN_IMPL
        if shards > 1:
            return kind, impl.mesh, impl.axis, shards
        return kind, None, None, 1
    return impl, None, None, 1


def set_prefix_attn_impl(impl: str) -> None:
    global PREFIX_ATTN_IMPL
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown prefix attention impl {impl!r}")
    PREFIX_ATTN_IMPL = impl


def prefix_attend_parts(q, qg, prefix_k, prefix_v, prefix_len, impl=None):
    """Flash partials (o, m, l) of queries vs the shared dense prefix.

    `q` is [B, S, n_heads, hd] post-RoPE (kernel layout); `qg` is the same
    queries pre-scaled and grouped [B, S, n_kv, g, hd] (einsum layout) —
    callers already have both, so the dispatch costs nothing. `impl`
    overrides the module default per call site (the engine plumbs its
    per-instance setting through; None falls back to PREFIX_ATTN_IMPL).
    """
    kind, mesh, axis, shards = _resolve_impl(impl)
    use_pallas = False
    if kind == "pallas" or (kind == "auto" and jax.default_backend() == "tpu"):
        from k8s_llm_scheduler_tpu.ops.pallas_prefix_attention import (
            prefix_attention_supported,
        )

        # "pallas" forces the kernel wherever the tiling supports it (incl.
        # interpret mode off-TPU — parity tests); unsupported shapes always
        # take the einsum path. On a sharded mesh the check runs on the
        # PER-SHARD shapes (kv heads divided over the tp axis).
        use_pallas = prefix_attention_supported(
            q.shape, prefix_k.shape[1], prefix_k.shape[0], shards=shards
        )
    if use_pallas:
        from k8s_llm_scheduler_tpu.ops.pallas_prefix_attention import (
            flash_prefix_attention_parts,
            flash_prefix_attention_parts_shmap,
        )

        if mesh is not None:
            return flash_prefix_attention_parts_shmap(
                q, prefix_k, prefix_v, prefix_len, mesh, axis
            )
        return flash_prefix_attention_parts(q, prefix_k, prefix_v, prefix_len)
    Sp = prefix_k.shape[0]
    pre_mask = (jnp.arange(Sp) < prefix_len)[None, None, None, None, :]
    return attend_part(qg, prefix_k, prefix_v, pre_mask, "bqkgh,skh->bkgqs")


def causal_chunk_attend_parts(q, qg, k_chunk, v_chunk, chunk_lens, impl=None):
    """Flash partials (o, m, l) of causal in-chunk self-attention.

    Same dispatch contract as prefix_attend_parts: `q` [B, S, n_heads, hd]
    post-RoPE for the kernel, `qg` the pre-scaled grouped layout for the
    einsum fallback."""
    kind, mesh, axis, shards = _resolve_impl(impl)
    use_pallas = False
    if kind == "pallas" or (kind == "auto" and jax.default_backend() == "tpu"):
        from k8s_llm_scheduler_tpu.ops.pallas_prefix_attention import (
            causal_attention_supported,
        )

        use_pallas = causal_attention_supported(
            q.shape, k_chunk.shape[2], shards=shards
        )
    if use_pallas:
        from k8s_llm_scheduler_tpu.ops.pallas_prefix_attention import (
            flash_causal_attention_parts,
            flash_causal_attention_parts_shmap,
        )

        if mesh is not None:
            return flash_causal_attention_parts_shmap(
                q, k_chunk, v_chunk, chunk_lens, mesh, axis
            )
        return flash_causal_attention_parts(q, k_chunk, v_chunk, chunk_lens)
    S = q.shape[1]
    pos = jnp.arange(S)
    causal = pos[:, None] >= pos[None, :]
    valid = pos[None, :] < chunk_lens[:, None]
    chunk_mask = causal[None, None, None, :, :] & valid[:, None, None, None, :]
    return attend_part(qg, k_chunk, v_chunk, chunk_mask, "bqkgh,bskh->bkgqs")


def causal_prefill_attention(
    q: jax.Array,  # [B, S, n_heads, head_dim]
    k: jax.Array,  # [B, S, n_kv_heads, head_dim]
    v: jax.Array,  # [B, S, n_kv_heads, head_dim]
    seq_lens: jax.Array,  # [B] valid lengths (padding beyond)
) -> jax.Array:
    """Causal self-attention over a (padded) prompt chunk, GQA-aware.

    One fused einsum chain — XLA tiles this well onto the MXU; bf16 inputs,
    f32 softmax accumulation.
    """
    B, S, n_heads, head_dim = q.shape
    n_kv = k.shape[2]
    q_per_kv = n_heads // n_kv

    # Group heads: [B, S, n_kv, q_per_kv, hd]
    qg = q.reshape(B, S, n_kv, q_per_kv, head_dim)
    scale = head_dim**-0.5
    logits = jnp.einsum(
        "bqkgh,bskh->bkgqs", qg.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )  # [B, n_kv, q_per_kv, S_q, S_kv]

    pos = jnp.arange(S)
    causal = pos[:, None] >= pos[None, :]  # [S_q, S_kv]
    valid = pos[None, :] < seq_lens[:, None]  # [B, S_kv]
    mask = causal[None, None, None, :, :] & valid[:, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskh->bqkgh", weights, v.astype(jnp.float32))
    return out.reshape(B, S, n_heads, head_dim).astype(q.dtype)


def gather_pages(
    cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    page_table: jax.Array,  # [B, max_pages]
) -> jax.Array:
    """Gather each sequence's pages into a contiguous view
    [B, max_pages*page_size, n_kv, head_dim]."""
    gathered = cache[page_table]  # [B, max_pages, page_size, n_kv, hd]
    B, P, psize, n_kv, hd = gathered.shape
    return gathered.reshape(B, P * psize, n_kv, hd)


def merge_attention_parts(parts):
    """Flash-style merge of partial-softmax attention parts.

    Each part is (o, m, l): o = exp(logits - m) @ V (unnormalized output),
    m = rowwise max logit, l = rowwise sum of exp(logits - m). Fully-masked
    parts contribute m = NEG_INF and therefore weight exp(NEG_INF - m*) = 0.
    """
    o_acc, m_acc, l_acc = parts[0]
    for o, m, l in parts[1:]:
        m_new = jnp.maximum(m_acc, m)
        w_acc = jnp.exp(m_acc - m_new)
        w = jnp.exp(m - m_new)
        o_acc = o_acc * w_acc[..., None] + o * w[..., None]
        l_acc = l_acc * w_acc + l * w
        m_acc = m_new
    return o_acc / jnp.maximum(l_acc, 1e-30)[..., None]


def attend_part(q_scaled, k, v, mask, kv_eq):
    """One softmax part: returns (o, m, l) for merge_attention_parts.

    q_scaled: [..., hd] f32 (already scaled); k/v: keys/values; mask selects
    valid kv positions. `kv_eq` is the einsum equation mapping q x k -> logits
    with the kv axis LAST; the output equation is derived by swapping k->v.
    """
    logits = jnp.einsum(kv_eq, q_scaled, k.astype(jnp.float32))
    logits = jnp.where(mask, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    # weights @ v over the kv axis (rhs's last letter); o aligns with m/l
    # dims plus a trailing head_dim so merge_attention_parts can broadcast.
    lhs, rhs = kv_eq.split("->")
    k_spec = lhs.split(",")[1]
    o = jnp.einsum(f"{rhs},{k_spec}->{rhs[:-1]}h", p, v.astype(jnp.float32))
    return o, m, l


def chunk_attention_with_prefix(
    q: jax.Array,  # [B, S, n_heads, head_dim] — suffix chunk queries
    k_chunk: jax.Array,  # [B, S, n_kv, head_dim]
    v_chunk: jax.Array,  # [B, S, n_kv, head_dim]
    chunk_lens: jax.Array,  # [B] valid suffix tokens per row
    prefix_k: jax.Array,  # [Sp, n_kv, head_dim] — SHARED dense prefix KV
    prefix_v: jax.Array,  # [Sp, n_kv, head_dim]
    prefix_len: jax.Array,  # scalar — valid prefix tokens
    prefix_impl: str | None = None,  # static — see prefix_attend_parts
) -> jax.Array:
    """Suffix-chunk attention with a shared dense prefix (cascade attention).

    Every suffix token attends to (a) the whole valid prefix — one einsum
    against a batch-free [Sp, ...] buffer, so the prefix KV is read from HBM
    once for the whole batch instead of once per sequence — and (b) causally
    within its own suffix chunk. The two softmax parts merge exactly via the
    log-sum-exp trick. prefix_len == 0 degrades to plain causal attention.

    This is the TPU-first answer to the burst-shared cluster-state prompt
    (core/prompt.py cluster_prefix; reference cache-key equivalence,
    reference scheduler.py:265-271): shared tokens become a dense MXU matmul
    instead of per-sequence paged gathers.
    """
    B, S, n_heads, head_dim = q.shape
    n_kv = k_chunk.shape[2]
    q_per_kv = n_heads // n_kv
    qg = (q.astype(jnp.float32) * head_dim**-0.5).reshape(B, S, n_kv, q_per_kv, head_dim)

    o_p, m_p, l_p = prefix_attend_parts(
        q, qg, prefix_k, prefix_v, prefix_len, impl=prefix_impl
    )  # o: [B, n_kv, g, S_q, hd]

    o_c, m_c, l_c = causal_chunk_attend_parts(
        q, qg, k_chunk, v_chunk, chunk_lens, impl=prefix_impl
    )

    out = merge_attention_parts([(o_p, m_p, l_p), (o_c, m_c, l_c)])  # [B,n_kv,g,S,hd]
    out = jnp.moveaxis(out, 3, 1)  # [B, S, n_kv, g, hd]
    return out.reshape(B, S, n_heads, head_dim).astype(q.dtype)


def paged_decode_attention(
    q: jax.Array,  # [B, n_heads, head_dim] — one new token per sequence
    k_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    v_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    page_table: jax.Array,  # [B, max_pages] page ids per sequence
    seq_lens: jax.Array,  # [B] length INCLUDING the new token
) -> jax.Array:
    """One decode step of attention against the paged KV cache.

    The new token's K/V must already be scattered into the cache (the model
    layer does that before calling). XLA path: gather pages then masked
    attention. The Pallas kernel version streams pages without
    materializing the gather.
    """
    B, n_heads, head_dim = q.shape
    n_kv = k_cache.shape[2]
    q_per_kv = n_heads // n_kv

    k = gather_pages(k_cache, page_table)  # [B, L, n_kv, hd]
    v = gather_pages(v_cache, page_table)
    L = k.shape[1]

    qg = q.reshape(B, n_kv, q_per_kv, head_dim)
    scale = head_dim**-0.5
    logits = jnp.einsum(
        "bkgh,blkh->bkgl", qg.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )  # [B, n_kv, q_per_kv, L]

    valid = jnp.arange(L)[None, :] < seq_lens[:, None]  # [B, L]
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgl,blkh->bkgh", weights, v.astype(jnp.float32))
    return out.reshape(B, n_heads, head_dim).astype(q.dtype)
