"""Hand-tiled Pallas TPU kernel for paged decode attention.

The XLA reference path (ops/attention.paged_decode_attention) materializes a
[B, max_pages*page_size, n_kv, hd] gather of every sequence's pages before
attending — an extra HBM round trip of the whole working set per decode
step. This kernel streams pages instead: the grid walks (sequence, page),
the page id comes from a SCALAR-PREFETCHED page table so Pallas can issue
the HBM->VMEM DMA for exactly the page each program needs (BlockSpec
index_map over the prefetch ref), and a flash-style running softmax
(m, l, acc scratch in VMEM) folds each page into the output without ever
materializing the gathered KV.

Semantics match paged_decode_attention exactly (same masking, GQA
handling, f32 accumulation); tests/test_pallas_attention.py asserts
equivalence against the XLA path. On non-TPU backends the kernel runs in
interpreter mode, so the hermetic CPU test suite exercises the same code
path the chip runs.

Replaces the remote attention the reference rents from the HF-hosted 70B
(reference scheduler.py:425-433) with an in-tree kernel on the hot decode
loop.
"""

from __future__ import annotations

import functools

import jax

from k8s_llm_scheduler_tpu.utils.jax_compat import (
    compiler_params,
    shard_map_compat,
)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, max_pages] int32 (SMEM)
    seq_lens_ref,    # [B] int32 (SMEM)
    # blocked inputs
    q_ref,   # [1, n_heads, hd]
    k_ref,   # [1, page_size, n_kv, hd] — the page this program attends to
    v_ref,   # [1, page_size, n_kv, hd]
    # blocked output(s): normalized [1, n_heads, hd], or with
    # normalize=False the flash partials (acc, m, l) for cascade merging
    *out_refs,
    normalize: bool,
):
    if normalize:
        (o_ref,), (m_scr, l_scr, acc_scr) = out_refs[:1], out_refs[1:]
    else:
        (acc_ref, m_ref, l_ref), (m_scr, l_scr, acc_scr) = (
            out_refs[:3],
            out_refs[3:],
        )
    b = pl.program_id(0)
    p = pl.program_id(1)
    page_size = k_ref.shape[1]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq_len = seq_lens_ref[b]
    start = p * page_size
    valid = seq_len - start  # tokens of this page inside the sequence

    @pl.when(valid > 0)
    def _attend():
        q = q_ref[0].astype(jnp.float32)  # [n_heads, hd]
        k = k_ref[0].astype(jnp.float32)  # [ps, n_kv, hd]
        v = v_ref[0].astype(jnp.float32)
        n_heads, hd = q.shape
        n_kv = k.shape[1]
        q_per_kv = n_heads // n_kv

        # GQA via a static per-KV-head loop of 2D matmuls (Mosaic lowers 2D
        # dot_general onto the MXU; 3D batched contractions don't lower).
        # Query head ordering matches the XLA path's reshape(n_kv, q_per_kv).
        scale = hd**-0.5
        score_blocks = []
        for kv in range(n_kv):
            q_blk = q[kv * q_per_kv : (kv + 1) * q_per_kv] * scale  # [qpk, hd]
            k_blk = k[:, kv, :]  # [ps, hd]
            score_blocks.append(
                jax.lax.dot_general(
                    q_blk, k_blk,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [qpk, ps]
            )
        scores = jnp.concatenate(score_blocks, axis=0)  # [n_heads, ps]

        inpage = jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1) < valid
        scores = jnp.where(inpage, scores, NEG_INF)

        m_prev = m_scr[:, :1]  # [n_heads, 1]
        m_page = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_page)
        alpha = jnp.exp(m_prev - m_new)  # rescale old accumulators
        probs = jnp.exp(scores - m_new)  # [n_heads, ps]
        probs = jnp.where(inpage, probs, 0.0)

        l_new = l_scr[:, :1] * alpha + jnp.sum(probs, axis=1, keepdims=True)
        pv_blocks = []
        for kv in range(n_kv):
            p_blk = probs[kv * q_per_kv : (kv + 1) * q_per_kv]  # [qpk, ps]
            v_blk = v[:, kv, :]  # [ps, hd]
            pv_blocks.append(
                jax.lax.dot_general(
                    p_blk, v_blk,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [qpk, hd]
            )
        pv = jnp.concatenate(pv_blocks, axis=0)  # [n_heads, hd]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p == pl.num_programs(1) - 1)
    def _finish():
        if normalize:
            denom = jnp.maximum(l_scr[:, :1], 1e-30)
            o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        else:
            acc_ref[0] = acc_scr[:]
            m_ref[0] = m_scr[:]
            l_ref[0] = l_scr[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(  # graftlint: ok[unconstrained-sharding] — single-device pallas kernel: the engine refuses this path on tp>1 meshes, there is nothing for GSPMD to partition
    q: jax.Array,  # [B, n_heads, head_dim] — one new token per sequence
    k_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    v_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages] page ids per sequence
    seq_lens: jax.Array,  # [B] length INCLUDING the new token
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in Pallas replacement for ops.attention.paged_decode_attention.

    Streams each sequence's pages HBM->VMEM via scalar-prefetched page ids
    and merges them with an on-chip flash accumulator — no gathered
    [B, max_pages*page_size, ...] intermediate.
    """
    B, n_heads, head_dim = q.shape
    num_pages, page_size, n_kv, _ = k_cache.shape
    max_pages = page_table.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    out = _paged_call(
        q, k_cache, v_cache, page_table, seq_lens,
        normalize=True, interpret=interpret,
    )
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_parts(  # graftlint: ok[unconstrained-sharding] — single-device pallas kernel: the engine refuses this path on tp>1 meshes, there is nothing for GSPMD to partition
    q: jax.Array,  # [B, n_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, page_size, n_kv, head_dim]
    v_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B] valid tokens in the paged region
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Flash PARTIALS over the paged region: (o, m, l) shaped
    ([B, n_kv, g, hd], [B, n_kv, g], [B, n_kv, g]) for
    ops.attention.merge_attention_parts — this is how the kernel joins the
    cascade (dense shared prefix | paged own tokens | chunk buffer) inside
    the engine's chunked decode without a materialized page gather.
    A fully-masked region (seq_len 0) reports m = NEG_INF, weight 0."""
    B, n_heads, head_dim = q.shape
    n_kv = k_cache.shape[2]
    g = n_heads // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    acc, m, l = _paged_call(
        q, k_cache, v_cache, page_table, seq_lens,
        normalize=False, interpret=interpret,
    )
    o = acc.reshape(B, n_kv, g, head_dim)
    return o, m[:, :, 0].reshape(B, n_kv, g), l[:, :, 0].reshape(B, n_kv, g)


def paged_decode_attention_parts_shmap(
    q, k_cache, v_cache, page_table, seq_lens, mesh, axis: str = "tp",
    interpret=None,
):
    """paged_decode_attention_parts with kv heads sharded over `mesh[axis]`.

    The paged KV cache shards its kv-head dim over tp
    (parallel/sharding.kv_cache_spec); page tables and seq lens replicate.
    Per shard the kernel is unchanged and collective-free, so shard_map is
    a pure layout wrapper (check_vma=False: pallas_call has no varying-axis
    rule)."""
    P = jax.sharding.PartitionSpec
    fn = functools.partial(paged_decode_attention_parts, interpret=interpret)
    return shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(
            P(None, axis, None),        # q [B, n_heads, hd]
            P(None, None, axis, None),  # caches [pages, ps, n_kv, hd]
            P(None, None, axis, None),
            P(None, None),              # page_table [B, max_pages]
            P(None),                    # seq_lens [B]
        ),
        out_specs=(
            P(None, axis, None, None),  # o [B, n_kv, g, hd]
            P(None, axis, None),        # m [B, n_kv, g]
            P(None, axis, None),
        ),
        check_vma=False,
    )(q, k_cache, v_cache, page_table, seq_lens)


def _paged_call(q, k_cache, v_cache, page_table, seq_lens, *, normalize, interpret):
    B, n_heads, head_dim = q.shape
    num_pages, page_size, n_kv, _ = k_cache.shape
    max_pages = page_table.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if normalize:
        out_shape = jax.ShapeDtypeStruct((B, n_heads, head_dim), q.dtype)
        out_specs = pl.BlockSpec(
            (1, n_heads, head_dim), lambda b, p, pt, sl: (b, 0, 0)
        )
    else:
        out_shape = (
            jax.ShapeDtypeStruct((B, n_heads, head_dim), jnp.float32),
            jax.ShapeDtypeStruct((B, n_heads, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, n_heads, 128), jnp.float32),
        )
        out_specs = (
            pl.BlockSpec((1, n_heads, head_dim), lambda b, p, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, n_heads, 128), lambda b, p, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, n_heads, 128), lambda b, p, pt, sl: (b, 0, 0)),
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec(
                (1, n_heads, head_dim), lambda b, p, pt, sl: (b, 0, 0)
            ),
            pl.BlockSpec(
                (1, page_size, n_kv, head_dim),
                lambda b, p, pt, sl: (pt[b, p], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, n_kv, head_dim),
                lambda b, p, pt, sl: (pt[b, p], 0, 0, 0),
            ),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((n_heads, 128), jnp.float32),
            pltpu.VMEM((n_heads, 128), jnp.float32),
            pltpu.VMEM((n_heads, head_dim), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, normalize=normalize),
        out_shape=out_shape,
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), q, k_cache, v_cache)
