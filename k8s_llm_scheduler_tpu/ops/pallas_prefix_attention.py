"""Hand-tiled Pallas TPU kernel for shared-prefix flash attention.

The XLA cascade path (ops/attention.attend_part on the prefix) materializes
a [B, n_kv, g, Sq, Sp] f32 score tensor per layer — at burst geometry
(16 rows x 512-token suffixes against a ~14k-token cluster-state prefix)
that is ~3 GB of HBM traffic per layer, and it dominates the decision-wave
latency (engine/engine.py _wave_impl). This kernel streams the prefix KV in
blocks with an online softmax instead: the grid walks
(kv_head, query_block, key_block), scores for one (q_block x k_block) tile
live in VMEM only, and a flash accumulator (m, l, acc scratch) folds each
key block into the output. Nothing [.., Sq, Sp]-shaped ever exists.

Emits UNNORMALIZED flash partials (o, m, l) in exactly the shapes
ops/attention.attend_part produces for the prefix part, so the caller
merges them with the in-chunk part via merge_attention_parts — the cascade
semantics (and tests) stay shared with the XLA path. Used by both cascade
callsites: the suffix prefill (models/llama._suffix_layer via
chunk_attention_with_prefix) and the wave block decode
(models/llama.forward_block_decode).

Replaces the remote prefill the reference pays per pod (reference
scheduler.py:425-433) with an in-tree flash kernel on the burst hot path.
"""

from __future__ import annotations

import functools

import jax

from k8s_llm_scheduler_tpu.utils.jax_compat import (
    compiler_params,
    shard_map_compat,
)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _largest_divisor(n: int, cap: int, multiple: int) -> int | None:
    """Largest d <= cap with n % d == 0 and d % multiple == 0."""
    for d in range(min(cap, n), multiple - 1, -1):
        if n % d == 0 and d % multiple == 0:
            return d
    return None


def _prefix_kernel(
    # scalar prefetch
    plen_ref,  # [1] int32 (SMEM) — valid prefix tokens
    # blocked inputs
    q_ref,  # [1, q_block, hd] f32, pre-scaled
    k_ref,  # [1, k_block, hd]
    v_ref,  # [1, k_block, hd]
    # blocked outputs
    o_ref,  # [1, q_block, hd] f32 (unnormalized flash acc)
    m_ref,  # [1, q_block, 128] f32 (running max, lane-broadcast)
    l_ref,  # [1, q_block, 128] f32 (running denom, lane-broadcast)
    # scratch
    m_scr,  # [q_block, 128]
    l_scr,  # [q_block, 128]
    acc_scr,  # [q_block, hd]
):
    kb = pl.program_id(2)
    k_block = k_ref.shape[1]

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = kb * k_block
    valid = plen_ref[0] - start  # prefix tokens inside this key block

    @pl.when(valid > 0)
    def _attend():
        # bf16 operands, f32 accumulation: the MXU's native mode (f32xf32
        # runs at a fraction of the rate). Standard flash practice; the
        # parity tests bound the error.
        q = q_ref[0].astype(jnp.bfloat16)  # [q_block, hd] (scaled by caller)
        k = k_ref[0].astype(jnp.bfloat16)  # [k_block, hd]
        v = v_ref[0].astype(jnp.bfloat16)
        scores = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [q_block, k_block]
        inblk = jax.lax.broadcasted_iota(jnp.int32, (1, k_block), 1) < valid
        scores = jnp.where(inblk, scores, NEG_INF)

        m_prev = m_scr[:, :1]  # [q_block, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new)
        probs = jnp.where(inblk, probs, 0.0)  # exp(NEG_INF-NEG_INF)=1 guard

        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(probs, axis=1, keepdims=True),
            l_scr.shape,
        )
        pv = jax.lax.dot_general(
            probs.astype(jnp.bfloat16), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [q_block, hd]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finish():
        o_ref[0] = acc_scr[:]
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def prefix_attention_supported(
    q_shape: tuple[int, ...], n_kv: int, prefix_cap: int, shards: int = 1
) -> bool:
    """Whether the kernel's tiling constraints hold for these static shapes.

    `shards` > 1 checks the PER-SHARD shapes of a shard_map over the
    kv-head axis (heads divided over tp; nq is unchanged since the GQA
    group size survives the division)."""
    B, S, n_heads, hd = q_shape
    if n_heads % shards or n_kv % shards:
        return False
    n_heads //= shards
    n_kv //= shards
    if n_heads % n_kv:
        return False
    nq = B * (n_heads // n_kv) * S  # query rows per kv head
    return (
        _largest_divisor(nq, 2048, 8) is not None
        and _largest_divisor(prefix_cap, 512, 128) is not None
    )


def _causal_kernel(
    # scalar prefetch
    lens_ref,  # [B] int32 (SMEM) — valid kv tokens per row
    # blocked inputs
    q_ref,  # [1, 1, q_block, hd] f32, pre-scaled
    k_ref,  # [1, 1, k_block, hd]
    v_ref,  # [1, 1, k_block, hd]
    # blocked outputs
    o_ref,  # [1, 1, q_block, hd] f32 (unnormalized flash acc)
    m_ref,  # [1, 1, q_block, 128]
    l_ref,  # [1, 1, q_block, 128]
    # scratch
    m_scr,  # [q_block, 128]
    l_scr,  # [q_block, 128]
    acc_scr,  # [q_block, hd]
    *,
    S: int,
    q_block: int,
    k_block: int,
):
    b = pl.program_id(0)
    qb = pl.program_id(2)
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # q rows are (g, s) flattened with q_block | S, so one block spans one
    # contiguous position range [p0, p0 + q_block) of a single query group.
    p0 = (qb * q_block) % S
    start = kb * k_block
    # contributes iff some kv position < min(lens, causal end)
    limit = jnp.minimum(lens_ref[b], p0 + q_block)

    @pl.when(limit > start)
    def _attend():
        q = q_ref[0, 0].astype(jnp.bfloat16)  # [q_block, hd] (scaled)
        k = k_ref[0, 0].astype(jnp.bfloat16)
        v = v_ref[0, 0].astype(jnp.bfloat16)
        scores = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [q_block, k_block]
        qpos = p0 + jax.lax.broadcasted_iota(jnp.int32, (q_block, 1), 0)
        kpos = start + jax.lax.broadcasted_iota(jnp.int32, (1, k_block), 1)
        mask = (kpos <= qpos) & (kpos < lens_ref[b])
        scores = jnp.where(mask, scores, NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new)
        probs = jnp.where(mask, probs, 0.0)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + jnp.sum(probs, axis=1, keepdims=True),
            l_scr.shape,
        )
        pv = jax.lax.dot_general(
            probs.astype(jnp.bfloat16), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kb == pl.num_programs(3) - 1)
    def _finish():
        o_ref[0, 0] = acc_scr[:]
        m_ref[0, 0] = m_scr[:]
        l_ref[0, 0] = l_scr[:]


def causal_attention_supported(
    q_shape: tuple[int, ...], n_kv: int, shards: int = 1
) -> bool:
    B, S, n_heads, hd = q_shape
    if n_heads % shards or n_kv % shards:
        return False
    if (n_heads // shards) % (n_kv // shards):
        return False
    return (
        _largest_divisor(S, 1024, 8) is not None
        and _largest_divisor(S, 1024, 128) is not None
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_causal_attention_parts(  # graftlint: ok[unconstrained-sharding] — single-device pallas kernel: the engine refuses this path on tp>1 meshes, there is nothing for GSPMD to partition
    q: jax.Array,  # [B, S, n_heads, hd] post-RoPE queries (UNscaled)
    k: jax.Array,  # [B, S, n_kv, hd]
    v: jax.Array,
    lens: jax.Array,  # [B] int32 — valid kv tokens per row
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Flash partials of causal self-attention within a chunk.

    Returns (o, m, l) shaped like
    ops.attention.attend_part(qg, k, v, mask, "bqkgh,bskh->bkgqs") —
    [B, n_kv, g, S, hd] f32 and [B, n_kv, g, S] — for
    merge_attention_parts. Upper-triangle key blocks are skipped entirely
    (~2x fewer tiles than a dense mask), and nothing [.., S, S]-shaped is
    materialized — the per-layer in-chunk score block of the chunked
    long-context prefill is ~540 MB at 1B/2048 on the XLA path.
    """
    B, S, n_heads, hd = q.shape
    n_kv = k.shape[2]
    g = n_heads // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q_block = _largest_divisor(S, 1024, 8)
    k_block = _largest_divisor(S, 1024, 128)
    if q_block is None or k_block is None:
        raise ValueError(f"unsupported chunk length {S} for flash causal attention")

    # [B, S, n_kv, g, hd] -> [B, n_kv, g, S, hd] -> [B, n_kv, g*S, hd]
    qr = q.reshape(B, S, n_kv, g, hd).transpose(0, 2, 3, 1, 4)
    qr = (qr.astype(jnp.float32) * hd**-0.5).reshape(B, n_kv, g * S, hd)
    kt = k.transpose(0, 2, 1, 3)  # [B, n_kv, S, hd]
    vt = v.transpose(0, 2, 1, 3)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, n_kv, g * S // q_block, S // k_block),
        in_specs=[
            pl.BlockSpec((1, 1, q_block, hd), lambda b, kv, qb, kb, l_: (b, kv, qb, 0)),
            pl.BlockSpec((1, 1, k_block, hd), lambda b, kv, qb, kb, l_: (b, kv, kb, 0)),
            pl.BlockSpec((1, 1, k_block, hd), lambda b, kv, qb, kb, l_: (b, kv, kb, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, q_block, hd), lambda b, kv, qb, kb, l_: (b, kv, qb, 0)),
            pl.BlockSpec((1, 1, q_block, 128), lambda b, kv, qb, kb, l_: (b, kv, qb, 0)),
            pl.BlockSpec((1, 1, q_block, 128), lambda b, kv, qb, kb, l_: (b, kv, qb, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, hd), jnp.float32),
        ],
    )
    o, m, l = pl.pallas_call(
        functools.partial(_causal_kernel, S=S, q_block=q_block, k_block=k_block),
        out_shape=(
            jax.ShapeDtypeStruct((B, n_kv, g * S, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, n_kv, g * S, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, n_kv, g * S, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(lens.astype(jnp.int32), qr, kt, vt)
    o = o.reshape(B, n_kv, g, S, hd)
    m = m[..., 0].reshape(B, n_kv, g, S)
    l = l[..., 0].reshape(B, n_kv, g, S)
    return o, m, l


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_prefix_attention_parts(  # graftlint: ok[unconstrained-sharding] — single-device pallas kernel: the engine refuses this path on tp>1 meshes, there is nothing for GSPMD to partition
    q: jax.Array,  # [B, S, n_heads, hd] post-RoPE queries (UNscaled)
    prefix_k: jax.Array,  # [Sp, n_kv, hd] shared dense prefix KV
    prefix_v: jax.Array,
    prefix_len: jax.Array,  # scalar int32 — valid prefix tokens
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Flash partials of suffix-queries vs the shared prefix.

    Returns (o, m, l) shaped ([B, n_kv, g, S, hd] f32, [B, n_kv, g, S],
    [B, n_kv, g, S]) — bit-compatible with
    ops.attention.attend_part(qg, prefix_k, prefix_v, mask, "bqkgh,skh->bkgqs")
    for merge_attention_parts. A fully-masked prefix (prefix_len == 0)
    reports m = NEG_INF, l = 0 (the merge then weights it to zero).
    """
    B, S, n_heads, hd = q.shape
    Sp, n_kv, _ = prefix_k.shape
    g = n_heads // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    nq = B * g * S
    q_block = _largest_divisor(nq, 1024, 8)
    k_block = _largest_divisor(Sp, 1024, 128)
    if q_block is None or k_block is None:
        raise ValueError(
            f"unsupported shapes for flash prefix attention: nq={nq}, Sp={Sp}"
        )

    # [B, S, n_kv, g, hd] -> [n_kv, B, g, S, hd] -> [n_kv, nq, hd]
    # (row index = (b*g + gi)*S + s; inverted exactly on the way out)
    qr = q.reshape(B, S, n_kv, g, hd).transpose(2, 0, 3, 1, 4)
    qr = (qr.astype(jnp.float32) * hd**-0.5).reshape(n_kv, nq, hd)
    # kv-head-major KV so key blocks tile (1, k_block, hd) — the Pallas TPU
    # lowering requires the last two block dims divisible by (8, 128) or
    # equal to the array dims. ~tens of MB of relayout vs the GBs of score
    # traffic the kernel eliminates.
    pk_t = prefix_k.transpose(1, 0, 2)  # [n_kv, Sp, hd]
    pv_t = prefix_v.transpose(1, 0, 2)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_kv, nq // q_block, Sp // k_block),
        in_specs=[
            pl.BlockSpec((1, q_block, hd), lambda kv, qb, kb, pl_: (kv, qb, 0)),
            pl.BlockSpec((1, k_block, hd), lambda kv, qb, kb, pl_: (kv, kb, 0)),
            pl.BlockSpec((1, k_block, hd), lambda kv, qb, kb, pl_: (kv, kb, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, q_block, hd), lambda kv, qb, kb, pl_: (kv, qb, 0)),
            pl.BlockSpec((1, q_block, 128), lambda kv, qb, kb, pl_: (kv, qb, 0)),
            pl.BlockSpec((1, q_block, 128), lambda kv, qb, kb, pl_: (kv, qb, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, hd), jnp.float32),
        ],
    )
    o, m, l = pl.pallas_call(
        _prefix_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n_kv, nq, hd), jnp.float32),
            jax.ShapeDtypeStruct((n_kv, nq, 128), jnp.float32),
            jax.ShapeDtypeStruct((n_kv, nq, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(
        jnp.asarray(prefix_len, dtype=jnp.int32).reshape(1),
        qr, pk_t, pv_t,
    )
    # [n_kv, nq, ...] -> [n_kv, B, g, S, ...] -> [B, n_kv, g, S, ...]
    o = o.reshape(n_kv, B, g, S, hd).transpose(1, 0, 2, 3, 4)
    m = m[:, :, 0].reshape(n_kv, B, g, S).transpose(1, 0, 2, 3)
    l = l[:, :, 0].reshape(n_kv, B, g, S).transpose(1, 0, 2, 3)
    return o, m, l


# ------------------------------------------------ tp-sharded (shard_map)
# GSPMD cannot partition a pallas_call, but both kernels are embarrassingly
# parallel over the kv-head axis — exactly the axis Megatron tp shards
# (parallel/sharding.py: wq/wk/wv column-parallel). Wrapping the kernel in
# shard_map over that axis runs one per-shard kernel per device with zero
# collectives; the flash partials come back kv-head-sharded, which is the
# layout merge_attention_parts and the wo row-parallel matmul expect.
# check_vma=False: pallas_call carries no varying-axis rule, and the wrap
# is collective-free by construction.


def flash_prefix_attention_parts_shmap(
    q, prefix_k, prefix_v, prefix_len, mesh, axis: str = "tp", interpret=None
):
    """flash_prefix_attention_parts with heads sharded over `mesh[axis]`."""
    P = jax.sharding.PartitionSpec
    fn = functools.partial(flash_prefix_attention_parts, interpret=interpret)
    return shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(
            P(None, None, axis, None),  # q [B, S, n_heads, hd]
            P(None, axis, None),        # prefix_k [Sp, n_kv, hd]
            P(None, axis, None),
            P(),                        # prefix_len scalar
        ),
        out_specs=(
            P(None, axis, None, None, None),  # o [B, n_kv, g, S, hd]
            P(None, axis, None, None),        # m [B, n_kv, g, S]
            P(None, axis, None, None),
        ),
        check_vma=False,
    )(q, prefix_k, prefix_v, jnp.asarray(prefix_len, jnp.int32))


def flash_causal_attention_parts_shmap(
    q, k, v, lens, mesh, axis: str = "tp", interpret=None
):
    """flash_causal_attention_parts with heads sharded over `mesh[axis]`."""
    P = jax.sharding.PartitionSpec
    fn = functools.partial(flash_causal_attention_parts, interpret=interpret)
    head_spec = P(None, None, axis, None)  # [B, S, heads, hd]
    return shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(head_spec, head_spec, head_spec, P(None)),
        out_specs=(
            P(None, axis, None, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
        ),
        check_vma=False,
    )(q, k, v, lens)
