"""Ragged-M Pallas matmul: skip dead decode-block columns on the MXU.

SCALING.md's wave roofline derives that 62% of block-decode compute at the
250-token operating point is F-width padding: each grammar-accelerated
iteration processes an [R, F] token block, but only the first len_r tokens
of each row are valid — and those counts are decided ON DEVICE by the DFA
walk, so no host-side bucketing can remove the padding (the dispatch round
trip costs more than it saves on a tunneled chip).

This kernel is the fix the roofline names. The engine compacts the valid
tokens to the FRONT of the flattened [M=R*F, K] activation (one argsort per
iteration, shared by all layers — models/llama.block_decode), and every
projection/MLP matmul runs here with the valid-token count scalar-
prefetched:

- grid (N/bn, K/bk), K innermost: each weight tile streams HBM->VMEM
  exactly once per call — weight traffic is identical to a dense matmul
  (an M-outer ragged grid would re-stream the full weight per M-tile,
  which at decode batch sizes is the dominant byte cost);
- the whole M extent of x and out live in VMEM blocks (decode M = R*F is
  a few hundred rows);
- the kernel body loops over ceil(total/bm) M-tiles with a dynamic
  fori_loop bound — FLOPs scale with the REAL token count, rounded up to
  bm, instead of with F*R.

Weights may be bf16 arrays or the int8 weight-only pairs from
models/quant.py ({"q", "scale"}): the q tile is converted next to the MXU
and the per-output-channel scale is applied outside (same contract as
models/llama._dense).

Equivalence vs the XLA dense path: tests/test_ragged_matmul.py (interpret
mode on CPU, same code path the chip runs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(total_ref, x_ref, w_ref, o_ref, *, bm: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    total = total_ref[0]
    m_tiles = (total + bm - 1) // bm
    w_tile = w_ref[...]
    if w_tile.dtype == jnp.int8:
        w_tile = w_tile.astype(jnp.bfloat16)

    def body(m, _):
        x_tile = x_ref[pl.ds(m * bm, bm), :]
        acc = jnp.dot(
            x_tile.astype(w_tile.dtype), w_tile,
            preferred_element_type=jnp.float32,
        )
        o_ref[pl.ds(m * bm, bm), :] += acc
        return 0

    jax.lax.fori_loop(0, m_tiles, body, 0, unroll=False)


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    rem = -size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def ragged_matmul(
    x: jax.Array,      # [M, K] activations, valid rows compacted to front
    w,                 # [K, N] bf16 | {"q": int8 [K, N], "scale": [1, N]}
    total: jax.Array,  # scalar int32: number of valid rows of x
    *,
    bm: int = 64,
    bn: int = 256,
    bk: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """out[:ceil(total/bm)*bm] = x @ w (+ dequant scale); rows beyond the
    last computed M-tile are ZERO. Output dtype follows x."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = isinstance(w, dict)
    w_arr = w["q"] if quantized else w
    M, K = x.shape
    Kw, N = w_arr.shape
    assert K == Kw, (x.shape, w_arr.shape)
    bn = min(bn, _ceil_mult(N, 128))
    bk = min(bk, _ceil_mult(K, 128))
    xp = _pad_to(x, 1, bk)
    wp = _pad_to(_pad_to(w_arr, 0, bk), 1, bn)
    mp = _pad_to(xp, 0, bm)
    grid = (wp.shape[1] // bn, wp.shape[0] // bk)
    out = pl.pallas_call(
        functools.partial(_kernel, bm=bm),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps take (*grid, *scalar_prefetch_refs)
                pl.BlockSpec((mp.shape[0], bk), lambda n, k, _t: (0, k)),
                pl.BlockSpec((bk, bn), lambda n, k, _t: (k, n)),
            ],
            out_specs=pl.BlockSpec(
                (mp.shape[0], bn), lambda n, k, _t: (0, n)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((mp.shape[0], wp.shape[1]), jnp.float32),
        interpret=interpret,
    )(jnp.atleast_1d(total).astype(jnp.int32), mp, wp)
    out = out[:M, :N]
    if quantized:
        out = out * w["scale"].reshape(1, -1)
    return out.astype(x.dtype)


def _ceil_mult(n: int, mult: int) -> int:
    return -(-n // mult) * mult
