"""Device mesh, GSPMD partition specs, and sequence-parallel attention."""
