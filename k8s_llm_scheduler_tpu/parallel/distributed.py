"""Multi-host (DCN) scaffolding: process initialization and hybrid meshes.

SURVEY §2.3's DP row names "batch axis replicated or sharded over DCN for
multi-host" as the TPU equivalent of the parallelism the reference rents
from HuggingFace's hosted deployment (reference scheduler.py:425-433,
config.yaml:9). This module provides the pieces:

- `init_distributed`: `jax.distributed.initialize` behind a flag — after
  it, `jax.devices()` spans every host and GSPMD collectives cross DCN.
- `multihost_mesh`: a mesh whose DCN axes (dp/fsdp — low-traffic
  collectives: one grad all-reduce per step) span processes while ICI axes
  (tp/sp — per-layer collectives) stay inside one host, so high-traffic
  collectives never leave the chip interconnect. This is the standard
  hybrid layout (cf. jax mesh_utils.create_hybrid_device_mesh); built by
  hand here so it works on any backend, including the virtual-CPU
  multi-process dryrun (tools/dryrun_multihost.py).
- `is_coordinator`: process-0 gate for cluster-facing side effects. The
  control plane (watch/bind) runs ONLY on the coordinator; worker hosts
  participate in collectives (training) or serve their own replica
  (serving — weights replicated across hosts over DCN, tp within host; see
  SCALING.md "Multi-host").
"""

from __future__ import annotations

import logging
import math
from collections.abc import Mapping

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

_INITIALIZED = False


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize multi-host JAX (idempotent; no-op for single-process).

    On TPU pods with the standard launcher the three arguments are
    auto-detected (pass None); on CPU/manual launch they are required.
    Returns True iff running multi-process after the call.
    """
    global _INITIALIZED
    if num_processes is not None and num_processes <= 1:
        return False
    if not _INITIALIZED:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _INITIALIZED = True
        logger.info(
            "distributed: process %d/%d, %d global devices",
            jax.process_index(), jax.process_count(), jax.device_count(),
        )
    return jax.process_count() > 1


def is_coordinator() -> bool:
    """True on the process that owns cluster-facing side effects
    (watch/bind); always True single-process."""
    return jax.process_index() == 0


def multihost_mesh(
    dcn_axes: Mapping[str, int],
    ici_axes: Mapping[str, int],
) -> Mesh:
    """Mesh with `dcn_axes` spanning processes and `ici_axes` within each.

    Axis order is (dcn..., ici...), so DCN axes are outermost — exactly the
    layout where per-layer tp/sp collectives ride ICI neighbors and only
    the once-per-step dp/fsdp reductions cross hosts.

    The product of dcn_axes must equal the process count; the product of
    ici_axes must fit each process's local device count (extra local
    devices are left out of the mesh).
    """
    # Size-1 axes are KEPT (like parallel/mesh.make_mesh): specs written
    # for the multi-host shape keep working on a scale-down mesh.
    dcn_axes = {k: int(v) for k, v in dcn_axes.items()}
    ici_axes = {k: int(v) for k, v in ici_axes.items()}
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(f"axes {overlap} appear in both dcn and ici")
    dcn_size = math.prod(dcn_axes.values()) if dcn_axes else 1
    ici_size = math.prod(ici_axes.values())
    procs = sorted({d.process_index for d in jax.devices()})
    if dcn_size != len(procs):
        raise ValueError(
            f"dcn axes {dict(dcn_axes)} need {dcn_size} processes, "
            f"have {len(procs)}"
        )
    rows = []
    for p in procs:
        local = sorted(
            (d for d in jax.devices() if d.process_index == p),
            key=lambda d: d.id,
        )
        if len(local) < ici_size:
            raise ValueError(
                f"ici axes {dict(ici_axes)} need {ici_size} devices per "
                f"process; process {p} has {len(local)}"
            )
        rows.append(local[:ici_size])
    arr = np.array(rows)  # [n_procs, ici_size]
    if dcn_axes:
        arr = arr.reshape(*dcn_axes.values(), *ici_axes.values())
    else:
        arr = arr[0].reshape(*ici_axes.values())
    return Mesh(arr, tuple(dcn_axes) + tuple(ici_axes))
