"""Device mesh construction from config.

The reference's only "distributed backend" is HTTPS to the HF router
(reference scheduler.py:343,425; SURVEY §2.3). Here distribution is a
`jax.sharding.Mesh` over TPU chips: the `llm.mesh` config block (the north
star's new field) names axis sizes, e.g. {dp: 1, tp: 8} for a v5p-16
tensor-parallel slice. XLA lowers all collectives (psum/all-gather/
reduce-scatter) over ICI from the shardings alone — no hand-written
NCCL/MPI analog exists or is needed.

Axis conventions used across the framework:
    dp    data/batch parallel (continuous-batching slots)
    fsdp  optional param sharding for training (weights scattered, gathered per layer)
    tp    tensor parallel (attention heads, MLP hidden dim)
    sp    sequence/context parallel (ring attention over long prompts)
"""

from __future__ import annotations

import math
from collections.abc import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from k8s_llm_scheduler_tpu.engine.sharded.geometry import MESH_AXES

# Mesh construction order == the declared axes table (one source of
# truth: engine/sharded/geometry.MESH_AXES, which graftlint's
# unknown-mesh-axis rule also validates PartitionSpec literals against).
AXIS_ORDER = MESH_AXES


def make_mesh(
    axes: Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh with named axes from an {axis: size} mapping.

    Axes of size 1 are kept (harmless, makes specs uniform). Axis order
    follows AXIS_ORDER so tp (highest-traffic collectives) maps to the
    innermost/fastest device dimension — on TPU that keeps TP traffic on
    ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {})
    for name in axes:
        if name not in AXIS_ORDER:
            raise ValueError(f"unknown mesh axis {name!r}; known: {AXIS_ORDER}")
    ordered = [(name, int(axes.get(name, 1))) for name in AXIS_ORDER if axes.get(name, 1) > 1]
    if not ordered:
        ordered = [("dp", 1)]
    total = math.prod(size for _, size in ordered)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(ordered)} needs {total} devices, have {len(devices)}"
        )
    names = tuple(name for name, _ in ordered)
    shape = tuple(size for _, size in ordered)
    grid = np.array(devices[:total]).reshape(shape)
    return Mesh(grid, names)


def mesh_from_config(
    mesh_cfg: Mapping[str, int] | None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh from the `llm.mesh` config block; defaults to all of one axis."""
    if not mesh_cfg:
        return make_mesh({"dp": 1}, devices=devices)
    return make_mesh(mesh_cfg, devices=devices)


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)
