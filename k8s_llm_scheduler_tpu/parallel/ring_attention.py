"""Ring attention: causal self-attention with the sequence sharded over a
mesh axis.

Long-context prefill support (SURVEY §5 long-context axis; BASELINE config 5
stresses an ~8k-token 256-node prompt — this module is what lets the same
design scale far beyond that). Each device holds one sequence chunk of
Q/K/V; K/V chunks rotate around the ring via `ppermute` while attention
accumulates blockwise with the streaming-softmax (log-sum-exp) correction,
so no device ever materializes the full [S, S] score matrix and the
communication pattern rides ICI neighbor links.

Pure-JAX implementation (einsum + fori_loop under shard_map) — XLA overlaps
the ppermute with the block computation. GQA-aware like ops/attention.py.
"""

from __future__ import annotations


import jax

from k8s_llm_scheduler_tpu.utils.jax_compat import (
    pvary_compat,
    shard_map_compat,
)
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_scheduler_tpu.ops.attention import NEG_INF


def _block_attn(q, k, v, q_pos, k_pos, scale, k_valid=None):
    """One (q-chunk x k-chunk) block: masked logits, local max/sum stats.

    q: [B, Sq, n_kv, g, hd]; k/v: [B, Sk, n_kv, hd]; k_valid: [B, Sk] bool
    per-row key validity (padding mask), None = all valid.
    Returns (num [B,Sq,n_kv,g,hd], den [B,Sq,n_kv,g], mx [B,Sq,n_kv,g]).
    """
    logits = jnp.einsum(
        "bqkgh,bskh->bqkgs", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    mask = (q_pos[:, None] >= k_pos[None, :])[None, :, None, None, :]  # causal
    if k_valid is not None:
        mask = mask & k_valid[:, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)
    mx = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - mx[..., None])
    # exp(NEG_INF - NEG_INF) = 1 on fully-masked rows — zero them so a
    # padded-out row reports den 0 (weight 0) instead of garbage mass.
    p = jnp.where(mask, p, 0.0)
    den = jnp.sum(p, axis=-1)
    num = jnp.einsum("bqkgs,bskh->bqkgh", p, v.astype(jnp.float32))
    return num, den, mx


def ring_self_attention(
    q: jax.Array,  # [B, S_local, n_heads, head_dim] — local sequence chunk
    k: jax.Array,  # [B, S_local, n_kv, head_dim]
    v: jax.Array,
    axis_name: str,
    varying_axes: tuple[str, ...] | None = None,
    seq_lens: jax.Array | None = None,  # [B] GLOBAL valid length per row
) -> jax.Array:
    """Causal ring attention over `axis_name`. Call inside shard_map with the
    sequence dim sharded over that axis. Chunks are assumed layed out in
    order: device i holds positions [i*S_local, (i+1)*S_local).

    `seq_lens` gives each row's global valid length: keys at absolute
    positions >= seq_lens[b] are masked out of every block, so padded
    batches attend only real tokens — matching unsharded masked attention
    (padding-row queries attend the row's valid prefix, exactly like
    ops.attention.causal_prefill_attention; loss masking drops them)."""
    B, S, n_heads, hd = q.shape
    n_kv = k.shape[2]
    g = n_heads // n_kv
    scale = hd**-0.5

    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    local_pos = jnp.arange(S)
    q_pos = me * S + local_pos

    qg = q.reshape(B, S, n_kv, g, hd)

    # Initial accumulators must be marked device-varying over every manual
    # axis of the enclosing shard_map (ring axis + optional batch axis) or
    # the fori_loop carry types mismatch (shard_map VMA tracking).
    axes = varying_axes if varying_axes is not None else (axis_name,)

    def _varying(x):
        return pvary_compat(x, axes)

    num0 = _varying(jnp.zeros((B, S, n_kv, g, hd), jnp.float32))
    den0 = _varying(jnp.zeros((B, S, n_kv, g), jnp.float32))
    mx0 = _varying(jnp.full((B, S, n_kv, g), NEG_INF, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(r, carry):
        k_cur, v_cur, num, den, mx = carry
        src = (me - r) % n  # whose chunk we hold after r rotations
        k_pos = src * S + local_pos
        k_valid = (
            None if seq_lens is None else k_pos[None, :] < seq_lens[:, None]
        )
        b_num, b_den, b_mx = _block_attn(
            qg, k_cur, v_cur, q_pos, k_pos, scale, k_valid
        )
        new_mx = jnp.maximum(mx, b_mx)
        corr_old = jnp.exp(mx - new_mx)
        corr_new = jnp.exp(b_mx - new_mx)
        num = num * corr_old[..., None] + b_num * corr_new[..., None]
        den = den * corr_old + b_den * corr_new
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, num, den, new_mx)

    k_f, v_f, num, den, mx = jax.lax.fori_loop(
        0, n, step, (k, v, num0, den0, mx0)
    )
    # den==0 only for a zero-length row (every key masked): the guard maps
    # it to 0 output instead of dividing by zero.
    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.reshape(B, S, n_heads, hd).astype(q.dtype)


def make_ring_prefill_attention(
    mesh: Mesh, sp_axis: str = "sp", batch_axis: str | None = None
):
    """shard_map-wrapped ring attention: takes full [B, S, H, hd] arrays with
    S sharded over `sp_axis` (and optionally B over `batch_axis`), returns
    the attention output with the same sharding. Signature-compatible with
    ops.attention.causal_prefill_attention so it can be passed as
    `attn_impl` to the model forward. `seq_lens` (per-row global valid
    length) masks padded key positions out of every ring block, so padded
    batches match unsharded masked attention — the round-2 NaN-poison
    guard is gone."""

    spec = P(batch_axis, sp_axis, None, None)
    varying = tuple(a for a in (sp_axis, batch_axis) if a)

    def wrapped(q, k, v, lens):
        return ring_self_attention(
            q, k, v, sp_axis, varying_axes=varying, seq_lens=lens
        )

    # check_vma=True (the pre-compat default): unlike the collective-free
    # pallas wrappers, the ring loop carries real ppermute collectives and
    # the pvary cast exists to satisfy exactly this verifier — keep it on
    # so a sharding bug fails loudly instead of attending garbage.
    wrapped = shard_map_compat(
        wrapped,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(batch_axis)),
        out_specs=spec,
        check_vma=True,
    )

    def attn(q, k, v, seq_lens=None):
        if seq_lens is None:
            seq_lens = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
        return wrapped(q, k, v, seq_lens.astype(jnp.int32))

    return attn
