"""Ring attention: causal self-attention with the sequence sharded over a
mesh axis.

Long-context prefill support (SURVEY §5 long-context axis; BASELINE config 5
stresses an ~8k-token 256-node prompt — this module is what lets the same
design scale far beyond that). Each device holds one sequence chunk of
Q/K/V; K/V chunks rotate around the ring via `ppermute` while attention
accumulates blockwise with the streaming-softmax (log-sum-exp) correction,
so no device ever materializes the full [S, S] score matrix and the
communication pattern rides ICI neighbor links.

Pure-JAX implementation (einsum + fori_loop under shard_map) — XLA overlaps
the ppermute with the block computation. GQA-aware like ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_llm_scheduler_tpu.ops.attention import NEG_INF


def _block_attn(q, k, v, q_pos, k_pos, scale):
    """One (q-chunk x k-chunk) block: masked logits, local max/sum stats.

    q: [B, Sq, n_kv, g, hd]; k/v: [B, Sk, n_kv, hd].
    Returns (num [B,Sq,n_kv,g,hd], den [B,Sq,n_kv,g], mx [B,Sq,n_kv,g]).
    """
    logits = jnp.einsum(
        "bqkgh,bskh->bqkgs", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    mask = q_pos[:, None] >= k_pos[None, :]  # causal by absolute position
    logits = jnp.where(mask[None, :, None, None, :], logits, NEG_INF)
    mx = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - mx[..., None])
    den = jnp.sum(p, axis=-1)
    num = jnp.einsum("bqkgs,bskh->bqkgh", p, v.astype(jnp.float32))
    return num, den, mx


def ring_self_attention(
    q: jax.Array,  # [B, S_local, n_heads, head_dim] — local sequence chunk
    k: jax.Array,  # [B, S_local, n_kv, head_dim]
    v: jax.Array,
    axis_name: str,
    varying_axes: tuple[str, ...] | None = None,
) -> jax.Array:
    """Causal ring attention over `axis_name`. Call inside shard_map with the
    sequence dim sharded over that axis. Chunks are assumed layed out in
    order: device i holds positions [i*S_local, (i+1)*S_local)."""
    B, S, n_heads, hd = q.shape
    n_kv = k.shape[2]
    g = n_heads // n_kv
    scale = hd**-0.5

    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    local_pos = jnp.arange(S)
    q_pos = me * S + local_pos

    qg = q.reshape(B, S, n_kv, g, hd)

    # Initial accumulators must be marked device-varying over every manual
    # axis of the enclosing shard_map (ring axis + optional batch axis) or
    # the fori_loop carry types mismatch (shard_map VMA tracking).
    axes = varying_axes if varying_axes is not None else (axis_name,)

    def _varying(x):
        return jax.lax.pcast(x, axes, to="varying")

    num0 = _varying(jnp.zeros((B, S, n_kv, g, hd), jnp.float32))
    den0 = _varying(jnp.zeros((B, S, n_kv, g), jnp.float32))
    mx0 = _varying(jnp.full((B, S, n_kv, g), NEG_INF, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(r, carry):
        k_cur, v_cur, num, den, mx = carry
        src = (me - r) % n  # whose chunk we hold after r rotations
        k_pos = src * S + local_pos
        b_num, b_den, b_mx = _block_attn(qg, k_cur, v_cur, q_pos, k_pos, scale)
        new_mx = jnp.maximum(mx, b_mx)
        corr_old = jnp.exp(mx - new_mx)
        corr_new = jnp.exp(b_mx - new_mx)
        num = num * corr_old[..., None] + b_num * corr_new[..., None]
        den = den * corr_old + b_den * corr_new
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, num, den, new_mx)

    k_f, v_f, num, den, mx = jax.lax.fori_loop(
        0, n, step, (k, v, num0, den0, mx0)
    )
    # Fully-masked rows (den==0 can't happen causally: position attends to
    # itself) — still guard the division.
    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.reshape(B, S, n_heads, hd).astype(q.dtype)


def make_ring_prefill_attention(
    mesh: Mesh, sp_axis: str = "sp", batch_axis: str | None = None
):
    """shard_map-wrapped ring attention: takes full [B, S, H, hd] arrays with
    S sharded over `sp_axis` (and optionally B over `batch_axis`), returns
    the attention output with the same sharding. Signature-compatible with
    ops.attention.causal_prefill_attention so it can be passed as
    `attn_impl` to the model forward; `seq_lens` is accepted but sequences
    must be full/unpadded (ring chunks have no per-chunk padding support)."""

    spec = P(batch_axis, sp_axis, None, None)
    varying = tuple(a for a in (sp_axis, batch_axis) if a)

    def wrapped(q, k, v):
        return ring_self_attention(q, k, v, sp_axis, varying_axes=varying)

    wrapped = functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(wrapped)

    def attn(q, k, v, seq_lens=None):
        if seq_lens is not None:
            # Loud guard instead of silent corruption: ring chunks carry no
            # per-chunk padding mask, so padded rows would attend pad K/V.
            # A padded batch NaN-poisons the output (surfaces in the loss)
            # rather than silently training on contaminated activations.
            ok = jnp.all(seq_lens == q.shape[1])
            q = jnp.where(ok, q, jnp.nan)
        return wrapped(q, k, v)

    return attn
