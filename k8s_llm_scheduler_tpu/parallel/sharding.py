"""GSPMD partition specs for the Llama param pytree and engine state.

Megatron-style tensor parallelism expressed purely as weight shardings —
XLA inserts the all-reduces (reference equivalent: whatever HF's hosted
deployment does server-side behind scheduler.py:425, invisible to the
reference's code):

- wq/wk/wv shard the HEAD (output) dim over tp  -> column parallel
- wo shards the head (input) dim over tp        -> row parallel, psum after
- w_gate/w_up shard d_ff over tp                -> column parallel
- w_down shards d_ff (input) over tp            -> row parallel, psum after
- embedding shards the vocab dim over tp (logits come out vocab-sharded,
  argmax/sample runs sharded then psums)
- layer norms replicated

The stacked-layer leading axis (L) is never sharded — scan iterates it.
An optional fsdp axis shards the remaining weight dim for training.
KV cache pages shard the kv-head dim over tp; page tables replicate.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import Params


def param_specs(
    cfg: LlamaConfig,
    tp: str | None = "tp",
    fsdp: str | None = None,
) -> Params:
    """PartitionSpec pytree matching models.llama.init_params structure."""
    specs: Params = {
        "embed": P(tp, None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fsdp, tp),
            "wk": P(None, fsdp, tp),
            "wv": P(None, fsdp, tp),
            "wo": P(None, tp, fsdp),
            "mlp_norm": P(None, None),
            "w_gate": P(None, fsdp, tp),
            "w_up": P(None, fsdp, tp),
            "w_down": P(None, tp, fsdp),
        },
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(fsdp, tp)
    return specs


def kv_cache_spec(tp: str | None = "tp") -> P:
    """[L, num_pages, page_size, n_kv, hd] — shard kv heads over tp."""
    return P(None, None, None, tp, None)


def shard_params(params: Params, mesh: Mesh, specs: Params | None = None,
                 cfg: LlamaConfig | None = None) -> Params:
    """Place a param pytree onto the mesh with NamedShardings."""
    if specs is None:
        assert cfg is not None, "need cfg to derive specs"
        specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def validate_specs_divisibility(cfg: LlamaConfig, mesh: Mesh, tp: str = "tp") -> None:
    """TP axis must divide heads/kv-heads/d_ff/vocab, or GSPMD pads
    inefficiently. Raise early with a clear message."""
    size = mesh.shape.get(tp, 1)
    problems = []
    if cfg.n_heads % size:
        problems.append(f"n_heads={cfg.n_heads} % tp={size}")
    if cfg.n_kv_heads % size:
        problems.append(f"n_kv_heads={cfg.n_kv_heads} % tp={size}")
    if cfg.d_ff % size:
        problems.append(f"d_ff={cfg.d_ff} % tp={size}")
    if cfg.vocab_size % size:
        problems.append(f"vocab={cfg.vocab_size} % tp={size}")
    if problems:
        raise ValueError(f"model {cfg.name} not divisible by tp axis: {problems}")
