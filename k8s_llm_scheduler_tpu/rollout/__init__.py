"""Live policy rollout: registry -> shadow -> canary gate -> hot swap.

The training loop (train/distill.py) produces servable checkpoints and the
arena (sim/arena.py) scores policies offline, but until this package the
serving engine loaded params exactly once (engine/local.build_local_backend)
and could never adopt a better policy without a restart that drops in-flight
scheduling traffic. This is the last mile of the improvement loop:

- registry.py  — versioned on-disk checkpoint registry (digests, lineage,
  arena scores, atomic publish, retention, fsck);
- hotswap.py   — zero-downtime weight swap for a running engine (quiesce at
  a wave barrier, restore direct-to-shard, swap the params reference,
  invalidate weight-derived state, bump the decision-cache generation);
- shadow.py    — non-binding mirroring of a fraction of live decisions
  through a candidate backend, scored against a stateless spread teacher;
- canary.py    — the promotion controller: seeded arena gate, promote via
  hot swap, burn-in regression monitoring, auto-rollback.
"""

from k8s_llm_scheduler_tpu.rollout.canary import (
    CanaryController,
    GateConfig,
    run_gate,
    staggered_swap,
)
from k8s_llm_scheduler_tpu.rollout.hotswap import HotSwapper, swap_engine_params
from k8s_llm_scheduler_tpu.rollout.registry import (
    CheckpointRegistry,
    Manifest,
    RegistryError,
    config_fingerprint,
)
from k8s_llm_scheduler_tpu.rollout.shadow import ShadowScorer, teacher_pick

__all__ = [
    "CanaryController",
    "CheckpointRegistry",
    "GateConfig",
    "HotSwapper",
    "Manifest",
    "RegistryError",
    "ShadowScorer",
    "config_fingerprint",
    "run_gate",
    "staggered_swap",
    "swap_engine_params",
    "teacher_pick",
]
