"""Promotion controller: arena gate -> hot swap -> burn-in -> rollback.

The full canary path for a candidate checkpoint:

1. **Gate** (offline, seeded): incumbent and candidate run END TO END as
   arena stack arms over the same seeded scenario (sim/arena.py — wire
   fake, real watch/bind, real scheduler loop). The candidate must be no
   worse than the incumbent within tolerance on the placement metrics the
   system optimizes: spread (lower better), constraint satisfaction and
   bound fraction (higher better). A fixed seed suite makes the verdict
   reproducible — a flaky gate is worse than no gate.
2. **Promote**: on pass, hot-swap the live engine (rollout/hotswap.py) and
   move the registry's active pointer. No restart, no dropped traffic.
3. **Burn-in**: watch the LIVE regression signals from Scheduler.get_stats
   deltas — fallback rate, invalid-decision rate, bind-failure rate — over
   a decision-count window. Offline gates can't see everything (real pod
   shapes, prompt drift); the burn-in can.
4. **Rollback**: any tripped signal swaps back to the prior registry
   version and marks the candidate rejected (it is not retried).

For fanout deployments (sched/replica.py), `staggered_swap` promotes one
replica at a time so the FanoutBackend always has a serving majority: each
replica's swap is verified before the next begins, and a failed swap stops
the stagger with the majority still on the incumbent.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Sequence

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class GateConfig:
    """The seeded scenario suite + tolerances for one gate run."""

    seed: int = 0
    nodes: int = 12
    pods: int = 48
    shapes: int = 8
    waves: int = 2
    constraint_mix: tuple[str, ...] = ("uniform", "selector")
    taint_frac: float = 0.0
    hetero: bool = True
    # candidate must satisfy: spread <= incumbent + spread_tolerance;
    # constraint_satisfaction >= incumbent - constraint_tolerance;
    # bound_frac >= incumbent - bound_tolerance
    spread_tolerance: float = 0.02
    constraint_tolerance: float = 0.0
    bound_tolerance: float = 0.0
    wave_timeout_s: float = 120.0


def run_gate(
    incumbent_make: Callable[[], Any],
    candidate_make: Callable[[], Any],
    gate: GateConfig | None = None,
) -> dict:
    """Run incumbent vs candidate through the seeded arena scenario and
    return the verdict: {"pass", "checks", "incumbent", "candidate",
    "seed"}. Backends built by the make() callables are owned by the arena
    (closed after the run)."""
    from k8s_llm_scheduler_tpu.sim import ArmSpec, generate_scenario, run_arena
    from k8s_llm_scheduler_tpu.sim.scenarios import ScenarioSpec

    gate = gate or GateConfig()
    spec = ScenarioSpec(
        name="canary-gate",
        seed=gate.seed,
        n_nodes=gate.nodes,
        n_pods=gate.pods,
        shapes=gate.shapes,
        arrival="waves",
        n_waves=gate.waves,
        constraint_mix=gate.constraint_mix,
        taint_frac=gate.taint_frac,
        hetero=gate.hetero,
    )
    scenario = generate_scenario(spec)
    report = run_arena(
        scenario,
        [
            ArmSpec(name="incumbent", kind="stack", make=incumbent_make),
            ArmSpec(name="candidate", kind="stack", make=candidate_make),
        ],
        wave_timeout_s=gate.wave_timeout_s,
    )
    inc = report["arms"]["incumbent"]["scores"]
    cand = report["arms"]["candidate"]["scores"]
    checks = {
        "spread": cand["spread"] <= inc["spread"] + gate.spread_tolerance,
        "constraint_satisfaction": (
            cand["constraint_satisfaction"]
            >= inc["constraint_satisfaction"] - gate.constraint_tolerance
        ),
        "bound_frac": (
            cand["bound_frac"] >= inc["bound_frac"] - gate.bound_tolerance
        ),
    }
    return {
        "pass": all(checks.values()),
        "checks": checks,
        "incumbent": inc,
        "candidate": cand,
        "seed": gate.seed,
        # the deterministic per-arm record (sim/trace.py shape): what the
        # learn loop's byte-compared trace embeds so a gate verdict can be
        # REPLAYED from its own placements instead of re-running two
        # backends (learn/loop.replay_learn_trace)
        "scenario_spec": spec.to_dict(),
        "traces": {
            name: {
                "placements": arm_trace["placements"],
                "unschedulable": arm_trace["unschedulable"],
                "scores": arm_trace["scores"],
            }
            for name, arm_trace in report["_traces"].items()
        },
    }


def staggered_swap(
    swap_fns: Sequence[Callable[[], Any]],
    verify: Callable[[int, Any], bool] | None = None,
    decision_cache: Any = None,
    kvplane_store: Any = None,
) -> list[Any]:
    """Run per-replica swap callables ONE AT A TIME (fanout and fleet
    deployments: the dispatch layer must always keep a serving majority
    on a consistent version). `verify(index, result)` returning False —
    or any raise — stops the stagger; replicas not yet swapped stay on
    the incumbent.

    `decision_cache` is the fleet's decision cache (typically
    fleet/cache.TieredDecisionCache over the shared L2): when every
    replica swapped successfully, its generation is bumped ONCE — one
    fleet-wide epoch, invalidating every replica's L1 and the shared L2
    coherently — instead of per-replica bumps that would leave windows
    where a not-yet-swapped replica refills the shared tier with
    old-policy decisions under the new epoch. On a stopped stagger the
    bump is withheld: the fleet is still serving the incumbent majority,
    and incumbent decisions remain valid.

    `kvplane_store` is the fleet's shared prefix-KV plane
    (fleet/kvplane/KVPlaneStore) and follows the identical
    once-on-completion discipline: its pages are prefix KV computed
    under the incumbent weights, valid for the incumbent majority during
    the stagger, and invalidated fleet-wide in ONE generation bump after
    the last replica swaps. Per-replica bumps would let a swapped
    replica republish new-weight pages while an unswapped peer still
    serves old weights — the exact mixed-epoch window the decision
    cache's single bump exists to close.

    Returns the per-replica results up to the stop point."""
    results: list[Any] = []
    completed = True
    for i, fn in enumerate(swap_fns):
        result = fn()
        results.append(result)
        if verify is not None and not verify(i, result):
            logger.warning(
                "staggered swap stopped at replica %d/%d (verify failed)",
                i + 1, len(swap_fns),
            )
            completed = False
            break
    if completed and decision_cache is not None:
        generation = decision_cache.bump_generation()
        logger.info(
            "staggered swap complete across %d replica(s); decision-cache "
            "generation bumped to %d", len(results), generation,
        )
    if completed and kvplane_store is not None:
        generation = kvplane_store.bump_generation()
        logger.info(
            "staggered swap complete; kvplane generation bumped to %d",
            generation,
        )
    return results


class CanaryController:
    """Watch the registry for candidates; gate, promote, burn in, roll back.

    Pluggable seams so the policy logic is testable without a model:
    `gate_runner(candidate_version) -> verdict dict` (defaults to run_gate
    over backend factories), `stats_provider() -> Scheduler.get_stats()`
    shape for burn-in monitoring, `clock` for deterministic tests."""

    def __init__(
        self,
        registry,
        swapper,                       # HotSwapper (or test double)
        *,
        stats_provider: Callable[[], dict] | None = None,
        gate_runner: Callable[[int], dict] | None = None,
        incumbent_factory: Callable[[], Any] | None = None,
        candidate_factory: Callable[[int], Callable[[], Any]] | None = None,
        gate: GateConfig | None = None,
        burn_in_decisions: int = 200,
        trip_fallback_rate: float = 0.2,
        trip_invalid_rate: float = 0.05,
        trip_bind_failure_rate: float = 0.05,
        trip_decide_p99_ms: float | None = None,
        slo_engine: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry
        self.swapper = swapper
        self.stats_provider = stats_provider
        self.gate = gate or GateConfig()
        if gate_runner is None:
            if incumbent_factory is None or candidate_factory is None:
                raise ValueError(
                    "CanaryController needs either gate_runner or both "
                    "incumbent_factory and candidate_factory"
                )

            def gate_runner(version: int) -> dict:
                return run_gate(
                    incumbent_factory, candidate_factory(version), self.gate
                )

        self.gate_runner = gate_runner
        self.burn_in_decisions = int(burn_in_decisions)
        self.trip_fallback_rate = float(trip_fallback_rate)
        self.trip_invalid_rate = float(trip_invalid_rate)
        self.trip_bind_failure_rate = float(trip_bind_failure_rate)
        # Optional latency trip: decide p99 over the burn-in WINDOW (from
        # PhaseRecorder histogram bucket deltas — a lifetime average would
        # dilute a fresh regression under the incumbent's history). None
        # disables; rates/percentiles are always recorded either way.
        self.trip_decide_p99_ms = (
            None if trip_decide_p99_ms is None else float(trip_decide_p99_ms)
        )
        # Optional SLO burn-rate input (observability/slo.SloEngine): a
        # tripped objective during an OPEN burn-in rolls back immediately
        # — the multiwindow burn rate is a stronger regression signal than
        # the window-count rates, and waiting out the decision count would
        # serve a burning SLO for the rest of the window.
        self.slo_engine = slo_engine
        self.clock = clock
        self.rejected: set[int] = set()
        self._burn: dict | None = None
        self.counters = {
            "gate_pass": 0,
            "gate_fail": 0,
            "promotions": 0,
            "rollbacks": 0,
        }
        self.last_gate: dict | None = None

    # ------------------------------------------------------------ baseline
    @staticmethod
    def _signals(stats: dict) -> dict[str, float]:
        client = stats.get("client", {})
        decisions = (
            stats.get("llm_decisions", 0)
            + stats.get("cache_decisions", 0)
            + stats.get("fallback_decisions", 0)
        )
        # Deadline/brownout sheds (sched/deadline.py ladder) ride the
        # fallback counter but indict the CALLER's load or an SLO burn,
        # not the candidate model — counting them would roll back a
        # healthy candidate the moment a brownout overlaps its burn-in.
        # degraded_fallbacks counts only the sheds that actually became
        # fallback DECISIONS (a shed that produced none lands in
        # `unschedulable`, and subtracting it would mask the candidate's
        # own fallbacks in the same window).
        degraded = float(client.get("degraded_fallbacks", 0))
        return {
            "decisions": float(decisions),
            "fallback": max(
                float(stats.get("fallback_decisions", 0)) - degraded, 0.0
            ),
            "invalid": float(client.get("invalid_decisions", 0)),
            "failed_bindings": float(stats.get("failed_bindings", 0)),
        }

    # ------------------------------------------------------------- promote
    def consider(self, version: int) -> dict:
        """Gate `version`; promote on pass (swap + active pointer + burn-in
        start). Returns the gate verdict augmented with the action taken."""
        verdict = dict(self.gate_runner(version))
        self.last_gate = {"version": version, **verdict}
        self.registry.record_scores(
            version, {"gate": {
                "pass": verdict["pass"], "checks": verdict["checks"],
                "candidate": verdict.get("candidate"),
            }}
        )
        if not verdict["pass"]:
            self.counters["gate_fail"] += 1
            self.rejected.add(version)
            verdict["action"] = "rejected"
            logger.info("canary gate REJECTED version %d: %s",
                        version, verdict["checks"])
            return verdict
        self.counters["gate_pass"] += 1
        prior = self.registry.active()
        try:
            swap = self.swapper.swap_to(version)
        except Exception as exc:
            # Gate passed but the swap refused (torn checkpoint, wrong
            # fingerprint, restore failure). Mark the version rejected —
            # retrying every tick would re-run the full arena gate plus a
            # restore attempt per poll period, forever, and starve newer
            # candidates behind it. The engine still serves the incumbent.
            self.rejected.add(version)
            self.registry.record_scores(
                version, {"swap_failed": str(exc)[:500]}
            )
            verdict["action"] = "swap_failed"
            verdict["error"] = str(exc)
            logger.exception(
                "gate passed but swap to version %d failed — rejected",
                version,
            )
            return verdict
        self.registry.set_active(version)
        self.counters["promotions"] += 1
        baseline = phases_baseline = None
        if self.stats_provider is not None:
            stats_now = self.stats_provider()
            baseline = self._signals(stats_now)
            # phases snapshot at promotion: burn-in latency percentiles
            # come from HISTOGRAM DELTAS against this (only the window's
            # own decisions, not lifetime averages)
            phases_baseline = stats_now.get("phases", {})
        self._burn = {
            "version": version,
            "prior": prior,
            "started": self.clock(),
            "baseline": baseline,
            "phases_baseline": phases_baseline,
        }
        verdict["action"] = "promoted"
        verdict["swap"] = swap
        logger.info(
            "canary gate PASSED version %d — promoted (pause %.1f ms)",
            version, swap.get("pause_s", 0.0) * 1000.0,
        )
        return verdict

    # ------------------------------------------------------------- burn-in
    def observe_burn_in(self) -> str | None:
        """Progress the burn-in window. Returns None (no burn-in / still
        collecting), "ok" (survived — burn-in closed), or "rolled_back"."""
        if self._burn is None or self.stats_provider is None:
            return None
        baseline = self._burn["baseline"]
        if baseline is None:
            self._burn = None
            return "ok"
        if self.slo_engine is not None:
            # SLO burn-rate trip during an open burn-in: roll back NOW —
            # no waiting for the decision-count window to fill while a
            # declared objective burns (observability/slo.py).
            slo_tripped = self.slo_engine.tripped()
            if slo_tripped:
                return self._roll_back(
                    tripped=[f"slo:{name}" for name in slo_tripped],
                    rates={"slo_tripped": slo_tripped},
                )
        now_stats = self.stats_provider()
        now_sig = self._signals(now_stats)
        delta_n = now_sig["decisions"] - baseline["decisions"]
        if delta_n < self.burn_in_decisions:
            return None
        rates = {
            "fallback_rate": (now_sig["fallback"] - baseline["fallback"]) / delta_n,
            "invalid_rate": (now_sig["invalid"] - baseline["invalid"]) / delta_n,
            "bind_failure_rate": (
                now_sig["failed_bindings"] - baseline["failed_bindings"]
            ) / delta_n,
        }
        # Window latency percentiles (histogram bucket deltas vs the
        # promotion-time snapshot): recorded always, tripping only when a
        # trip_decide_p99_ms budget is configured.
        from k8s_llm_scheduler_tpu.observability.trace import (
            delta_hist,
            hist_percentiles,
        )

        phases_base = self._burn.get("phases_baseline") or {}
        dh = delta_hist(
            phases_base.get("decide"),
            now_stats.get("phases", {}).get("decide"),
        )
        if dh and dh["count"]:
            p50, p95, p99 = hist_percentiles(dh["counts"])
            rates["decide_p50_ms"] = round(p50, 3)
            rates["decide_p95_ms"] = round(p95, 3)
            rates["decide_p99_ms"] = round(p99, 3)
        trips = {
            "fallback_rate": rates["fallback_rate"] > self.trip_fallback_rate,
            "invalid_rate": rates["invalid_rate"] > self.trip_invalid_rate,
            "bind_failure_rate": (
                rates["bind_failure_rate"] > self.trip_bind_failure_rate
            ),
        }
        if (
            self.trip_decide_p99_ms is not None
            and "decide_p99_ms" in rates
        ):
            # The percentile estimate is the UPPER bound of a 2x-spaced
            # bucket (observability/trace.hist_percentiles), so the true
            # p99 lies in (est/2, est]. Trip on the LOWER bound: est/2 >
            # budget guarantees the true p99 exceeded it — comparing the
            # upper bound directly would spuriously roll back healthy
            # candidates whose true p99 sits at ~half the budget.
            trips["decide_p99_ms"] = (
                rates["decide_p99_ms"] / 2.0 > self.trip_decide_p99_ms
            )
        if any(trips.values()):
            return self._roll_back(
                tripped=sorted(k for k, v in trips.items() if v),
                rates=rates,
            )
        version = self._burn["version"]
        self._burn = None
        self.registry.record_scores(
            version, {"burn_in": {"tripped": [], "rates": rates}}
        )
        logger.info("burn-in OK for version %d (rates %s)", version, rates)
        return "ok"

    def _roll_back(self, tripped: list, rates: dict) -> str:
        """Close the open burn-in as TRIPPED: reject the candidate, swap
        back to the prior version, bump counters. Shared by the window
        rate trips and the SLO burn-rate early trip."""
        version = self._burn["version"]
        prior = self._burn["prior"]
        self._burn = None
        logger.warning(
            "burn-in TRIPPED for version %d (%s; rates %s) — rolling "
            "back to %s", version, tripped, rates, prior,
        )
        self.rejected.add(version)
        self.registry.record_scores(
            version, {"burn_in": {"tripped": tripped, "rates": rates}}
        )
        if prior is not None:
            self.swapper.swap_to(prior)
            self.registry.set_active(prior)
        self.counters["rollbacks"] += 1
        return "rolled_back"

    # ----------------------------------------------------------------- tick
    def tick(self) -> dict | str | None:
        """One controller step: finish an open burn-in first, else gate the
        newest un-rejected candidate above the active version."""
        if self._burn is not None:
            return self.observe_burn_in()
        active = self.registry.active() or 0
        candidates = [
            v for v in self.registry.versions()
            if v > active and v not in self.rejected
        ]
        if not candidates:
            return None
        return self.consider(candidates[-1])

    def pinned_versions(self) -> set[int]:
        """Versions the registry's retention walk must not evict on this
        controller's account: the OPEN burn-in candidate and its rollback
        target. Mid burn-in the candidate IS the active version, but its
        prior may sit outside the keep-last window — evicting it turns
        the next rollback into a RegistryError (rollout/registry.retain's
        pinned set exists for exactly this and the incident-corpus
        lineage case)."""
        pinned: set[int] = set()
        if self._burn is not None:
            pinned.add(int(self._burn["version"]))
            if self._burn["prior"] is not None:
                pinned.add(int(self._burn["prior"]))
        return pinned

    def stats(self) -> dict:
        out = {
            **self.counters,
            "active_version": self.registry.active(),
            "burn_in_open": self._burn is not None,
            "rejected": sorted(self.rejected),
        }
        if self._burn is not None:
            out["candidate_version"] = self._burn["version"]
        if self.last_gate is not None:
            out["last_gate_version"] = self.last_gate["version"]
            out["last_gate_pass"] = bool(self.last_gate["pass"])
        if hasattr(self.swapper, "stats"):
            out["swap"] = self.swapper.stats()
        return out
