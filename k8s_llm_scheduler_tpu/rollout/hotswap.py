"""Zero-downtime weight swap for a running LocalLLMBackend.

The serving engine is single-owner (one engine thread drives every device
dispatch — engine/local.py), so a swap is not a lock dance: it is a control
item on that thread's queue. `LocalLLMBackend.run_quiesced` holds new
admissions, drains every in-flight wave (no request fails or drops — held
work waits out the pause and resumes the next tick), and runs the swap at
the barrier. The admission-held wall time IS the reported swap pause.

Two residency modes, because 2x params does not always fit:

- **double** (default when it fits): restore the candidate direct-to-shard
  onto the SERVING mesh with the existing tp specs while the old params
  keep serving; the quiesced window is only the pointer swap + state
  invalidation (sub-second). The old tree is returned to the caller and
  held until the candidate survives burn-in — instant rollback.
- **donate** (70B-class, no 2x HBM headroom): the old params are released
  FIRST, then the candidate restores into the freed memory inside the
  quiesced window. The pause covers the whole restore, and a failed
  restore leaves the engine paramless — the swapper re-restores the prior
  version from the registry (disk is the rollback buffer, not HBM).

What a swap invalidates (everything computed under the old weights):
- on-device prefix-KV cache + active prefix (engine.swap_params);
- the decision cache ABOVE the engine via a generation bump
  (core/cache.py) — cached decisions are the old policy's outputs and
  must never be served after promotion;
- spec-draft state is per-request (spec/decoder.py) and the target params
  are read live at dispatch, so the paged/spec paths need no extra work.
"""

from __future__ import annotations

import logging
from typing import Any

from k8s_llm_scheduler_tpu.models.loader import CheckpointError, restore_checkpoint
from k8s_llm_scheduler_tpu.observability.trace import PhaseRecorder
from k8s_llm_scheduler_tpu.rollout.registry import (
    CheckpointRegistry,
    RegistryError,
    config_fingerprint,
)

logger = logging.getLogger(__name__)


def swap_engine_params(engine, params) -> Any:
    """Engine-level swap (see InferenceEngine.swap_params): replace the
    served weights and invalidate weight-derived device state. Returns the
    old params tree. Callers outside the engine-owner thread must go
    through HotSwapper / run_quiesced."""
    return engine.swap_params(params)


def _tree_bytes(params) -> int:
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(params)
    )


def _device_headroom_bytes() -> int | None:
    """Free device memory on the first device, or None when the backend
    doesn't report it (CPU, some drivers) — callers treat None as 'room'."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats or "bytes_limit" not in stats:
        return None
    return int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))


class HotSwapper:
    """Promote registry versions into a live LocalLLMBackend.

    Owns: digest verification before any restore, config-fingerprint
    matching, residency-mode choice (double vs donate), the quiesced
    install, the decision-cache generation bump, and the swap-pause /
    phase accounting surfaced to /metrics."""

    def __init__(
        self,
        backend,                      # LocalLLMBackend (has .engine, .run_quiesced)
        registry: CheckpointRegistry,
        cfg,                          # the serving LlamaConfig
        *,
        mesh=None,                    # the SERVING mesh (None = single device)
        tp: str | None = "tp",
        fsdp: str | None = None,
        cache=None,                   # DecisionCache to generation-bump
        kvplane=None,                 # fleet KVPlaneStore to generation-bump
        mode: str = "auto",           # auto | double | donate
        quantize: str | None = None,  # None | "int8" — match the serving tree
        verify_digests: bool = True,
    ) -> None:
        if mode not in ("auto", "double", "donate"):
            raise ValueError(f"unknown swap mode {mode!r}")
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantization {quantize!r} (only 'int8')")
        self.backend = backend
        self.registry = registry
        self.cfg = cfg
        self.mesh = mesh
        self.tp = tp
        self.fsdp = fsdp
        self.cache = cache
        # Shared prefix-KV plane store: its generation is the FLEET-wide
        # twin of engine.prefix_epoch — peers' published prefix pages
        # were prefilled under the outgoing weights, so the swap must
        # invalidate them everywhere, not just on this replica.
        self.kvplane = kvplane
        self.mode = mode
        self.quantize = quantize
        self.verify_digests = verify_digests
        self.phases = PhaseRecorder()
        self.active_version: int | None = registry.active()
        self._prior_version: int | None = None
        self.stats_counters = {
            "swaps": 0,
            "rollbacks": 0,
            "last_pause_s": 0.0,
            "last_mode": "",
        }

    # ----------------------------------------------------------- residency
    def _choose_mode(self) -> str:
        if self.mode != "auto":
            return self.mode
        params_bytes = _tree_bytes(self.backend.engine.params)
        headroom = _device_headroom_bytes()
        if headroom is not None and headroom < params_bytes:
            logger.info(
                "swap mode=donate: %.2f GB params vs %.2f GB HBM headroom "
                "(double-buffering needs a full second copy)",
                params_bytes / 1e9, headroom / 1e9,
            )
            return "donate"
        return "double"

    def _restore(self, manifest) -> Any:
        """Restore a registry version shaped exactly like the serving tree:
        same mesh/specs, same quantization — engine programs were compiled
        against that tree's shardings and dtypes."""
        params = restore_checkpoint(
            manifest.checkpoint_path, self.cfg, self.mesh,
            tp=self.tp, fsdp=self.fsdp,
        )
        if self.quantize == "int8":
            from k8s_llm_scheduler_tpu.models.quant import quantize_params

            params = quantize_params(params)
        return params

    # ---------------------------------------------------------------- swap
    def _check_version(self, version: int) -> "Any":
        manifest = self.registry.get(version)
        if self.verify_digests:
            ok, problems = self.registry.verify(version)
            if not ok:
                raise CheckpointError(
                    f"registry version {version} failed digest verification "
                    f"before swap: {problems[:3]}"
                )
        want = config_fingerprint(self.cfg)
        if manifest.config_fingerprint and manifest.config_fingerprint != want:
            raise CheckpointError(
                f"registry version {version} is shaped for config "
                f"{manifest.config_name!r} (fingerprint "
                f"{manifest.config_fingerprint}), serving config is "
                f"{self.cfg.name!r} ({want})"
            )
        return manifest

    def swap_to(self, version: int) -> dict:
        """Hot-swap the live engine to `version`. Returns
        {"version", "prior", "pause_s", "mode"}. Raises CheckpointError /
        RegistryError with the engine still serving the OLD weights (double
        mode) or restored to them from disk (donate mode)."""
        manifest = self._check_version(version)
        mode = self._choose_mode()
        engine = self.backend.engine
        prior = self.active_version

        if mode == "double":
            # load OUTSIDE the quiesced window: old params serve throughout
            with self.phases.phase("swap_load"):
                new_params = self._restore(manifest)

            def install():
                with self.phases.phase("swap_install"):
                    return engine.swap_params(new_params)

            old_params, pause_s = self.backend.run_quiesced(install)
            # old tree dropped here: burn-in rollback restores from the
            # registry (double-buffering covers the SWAP, not the burn-in —
            # holding 2x HBM for a whole burn-in window would starve the
            # prefix cache)
            del old_params
        else:
            def install():
                with self.phases.phase("swap_install"):
                    engine.params = None  # release before restore: no 2x
                    try:
                        new_params = self._restore(manifest)
                    except Exception:
                        # engine is paramless — restore the prior version
                        # from disk before propagating, or serving is dead
                        if prior is not None:
                            engine.params = self._restore(
                                self.registry.get(prior)
                            )
                        raise
                    return engine.swap_params(new_params)

            _, pause_s = self.backend.run_quiesced(install)

        if self.cache is not None:
            self.cache.bump_generation()
        if self.kvplane is not None:
            self.kvplane.bump_generation()
        self._prior_version = prior
        self.active_version = version
        self.stats_counters["swaps"] += 1
        self.stats_counters["last_pause_s"] = round(pause_s, 6)
        self.stats_counters["last_mode"] = mode
        logger.info(
            "hot-swapped to version %d (mode=%s, pause=%.1f ms, prior=%s)",
            version, mode, pause_s * 1000.0, prior,
        )
        return {
            "version": version,
            "prior": prior,
            "pause_s": pause_s,
            "mode": mode,
        }

    def rollback(self) -> dict:
        """Swap back to the version active before the last swap_to (burn-in
        trip path). Falls back to the active version's manifest parent when
        the in-memory prior is unknown (fresh controller)."""
        target = self._prior_version
        if target is None and self.active_version is not None:
            target = self.registry.get(self.active_version).parent
        if target is None:
            raise RegistryError("no prior version to roll back to")
        out = self.swap_to(target)
        self.stats_counters["rollbacks"] += 1
        return out

    def stats(self) -> dict:
        return {
            **self.stats_counters,
            "active_version": self.active_version,
            "phases": self.phases.snapshot(),
        }
