"""Versioned checkpoint registry: the durable handoff between train and serve.

`train/distill.train_and_save` writes an orbax checkpoint dir;
`CheckpointRegistry.publish` copies it into the registry under a monotonic
version id with a manifest recording everything promotion needs to trust it:

- a config fingerprint (the LlamaConfig the params are shaped for — a
  candidate shaped for a different config must be rejected before it ever
  reaches a mesh);
- per-file content digests (`verify` recomputes them, so a torn copy,
  truncated upload, or tampered file is caught before restore);
- lineage (parent version) and recorded arena scores.

Publish is ATOMIC with the same write-aside + rename discipline as
models/loader.save_checkpoint: everything lands in a staging dir first and
one rename makes the version visible — a crash mid-publish leaves only a
`.staging-*` dir that the next publish sweeps, never a half-readable
version. The pointer file (active version, next id) updates via
write-tmp + os.replace for the same reason.

Single-writer by design: one trainer/controller process publishes and
promotes; serving processes only read. Version ids stay monotonic across
retention deletes (the pointer file remembers `next_version`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import time
from pathlib import Path
from typing import Any, Iterable

logger = logging.getLogger(__name__)

_VERSION_FMT = "v{:06d}"
_MANIFEST = "manifest.json"
_CHECKPOINT = "checkpoint"
_POINTER = "registry.json"


class RegistryError(RuntimeError):
    """A registry operation failed (unknown version, digest mismatch...)."""


def config_fingerprint(cfg: Any) -> str:
    """Stable digest of a LlamaConfig's architecture-defining fields.

    Serving must only hot-swap a checkpoint whose fingerprint matches the
    engine's config — same shapes, same sharding specs, same compiled
    programs. dtype is stringified (jnp dtypes don't JSON-serialize) and
    nested dataclasses (RopeScaling) flatten through asdict."""
    d = dataclasses.asdict(cfg)
    d["dtype"] = str(d.get("dtype"))
    blob = json.dumps(d, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _file_digest(path: Path) -> tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


@dataclasses.dataclass
class Manifest:
    """One published version's metadata (the on-disk manifest.json)."""

    version: int
    config_name: str
    config_fingerprint: str
    tokenizer: str
    created_at: float
    parent: int | None = None
    scores: dict[str, Any] = dataclasses.field(default_factory=dict)
    note: str = ""
    # relpath under checkpoint/ -> {"sha256": ..., "bytes": ...}
    files: dict[str, dict[str, Any]] = dataclasses.field(default_factory=dict)
    # filled by the registry on load; never serialized
    checkpoint_path: Path | None = dataclasses.field(default=None, repr=False)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("checkpoint_path")
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Manifest":
        return cls(**{k: v for k, v in d.items() if k != "checkpoint_path"})


class CheckpointRegistry:
    """On-disk registry: <root>/v000001/{manifest.json, checkpoint/...}."""

    def __init__(self, root: str | Path) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # sweep staging debris from a crashed publish — never a visible
        # version, always safe to delete
        for stale in self.root.glob(".staging-*"):
            shutil.rmtree(stale, ignore_errors=True)

    # ------------------------------------------------------------- pointer
    def _pointer(self) -> dict:
        p = self.root / _POINTER
        if not p.exists():
            return {"active": None, "next_version": 1}
        with open(p) as fh:
            return json.load(fh)

    def _write_pointer(self, data: dict) -> None:
        tmp = self.root / (_POINTER + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.root / _POINTER)

    def active(self) -> int | None:
        return self._pointer()["active"]

    def set_active(self, version: int | None) -> None:
        if version is not None:
            self.get(version)  # raises RegistryError on an unknown version
        ptr = self._pointer()
        ptr["active"] = version
        self._write_pointer(ptr)

    # ------------------------------------------------------------ versions
    def _version_dir(self, version: int) -> Path:
        return self.root / _VERSION_FMT.format(version)

    def versions(self) -> list[int]:
        out = []
        for d in self.root.iterdir():
            if d.is_dir() and d.name.startswith("v") and (d / _MANIFEST).exists():
                try:
                    out.append(int(d.name[1:]))
                except ValueError:
                    continue
        return sorted(out)

    def latest(self) -> Manifest | None:
        versions = self.versions()
        return self.get(versions[-1]) if versions else None

    def get(self, version: int) -> Manifest:
        vdir = self._version_dir(version)
        manifest_path = vdir / _MANIFEST
        if not manifest_path.exists():
            raise RegistryError(
                f"registry {self.root}: no version {version} "
                f"(have {self.versions()})"
            )
        with open(manifest_path) as fh:
            manifest = Manifest.from_dict(json.load(fh))
        manifest.checkpoint_path = vdir / _CHECKPOINT
        return manifest

    # ------------------------------------------------------------- publish
    def publish(
        self,
        checkpoint_dir: str | Path,
        *,
        cfg: Any = None,
        config_name: str = "",
        tokenizer: str = "byte",
        parent: int | None = None,
        scores: dict | None = None,
        note: str = "",
    ) -> Manifest:
        """Copy `checkpoint_dir` into the registry as the next version.

        Digests are computed WHILE copying (one read of each file), the
        manifest is written into the staging dir, and a single rename
        publishes the version. `cfg` (a LlamaConfig) stamps the config
        fingerprint; passing only `config_name` records the name without a
        fingerprint (fingerprint-less versions never pass a fingerprint
        check at swap time)."""
        src = Path(checkpoint_dir)
        if not src.is_dir():
            raise RegistryError(f"checkpoint dir {src} does not exist")
        ptr = self._pointer()
        version = int(ptr["next_version"])
        staging = self.root / f".staging-{_VERSION_FMT.format(version)}-{os.getpid()}"
        if staging.exists():
            shutil.rmtree(staging)
        dst = staging / _CHECKPOINT
        files: dict[str, dict[str, Any]] = {}
        try:
            for path in sorted(src.rglob("*")):
                rel = path.relative_to(src)
                target = dst / rel
                if path.is_dir():
                    target.mkdir(parents=True, exist_ok=True)
                    continue
                target.parent.mkdir(parents=True, exist_ok=True)
                shutil.copyfile(path, target)
                digest, size = _file_digest(target)
                files[str(rel)] = {"sha256": digest, "bytes": size}
            if not files:
                raise RegistryError(f"checkpoint dir {src} is empty")
            manifest = Manifest(
                version=version,
                config_name=(
                    config_name or (getattr(cfg, "name", "") if cfg else "")
                ),
                config_fingerprint=config_fingerprint(cfg) if cfg else "",
                tokenizer=tokenizer,
                created_at=time.time(),  # graftlint: ok[raw-clock, wall-clock-in-replay] — wall-clock metadata for operators, never compared against durations
                parent=parent if parent is not None else self.active(),
                scores=dict(scores or {}),
                note=note,
                files=files,
            )
            with open(staging / _MANIFEST, "w", encoding="utf-8") as fh:
                json.dump(manifest.to_dict(), fh, indent=1, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            final = self._version_dir(version)
            os.rename(staging, final)  # the atomic publish
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        ptr["next_version"] = version + 1
        self._write_pointer(ptr)
        manifest.checkpoint_path = final / _CHECKPOINT
        logger.info(
            "published checkpoint version %d (%d files, parent=%s)",
            version, len(files), manifest.parent,
        )
        return manifest

    def record_scores(self, version: int, scores: dict) -> None:
        """Merge arena/gate scores into a version's manifest (atomic)."""
        manifest = self.get(version)
        manifest.scores.update(scores)
        vdir = self._version_dir(version)
        tmp = vdir / (_MANIFEST + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest.to_dict(), fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, vdir / _MANIFEST)

    # -------------------------------------------------------------- verify
    def verify(self, version: int) -> tuple[bool, list[str]]:
        """Digest-check every file of a version against its manifest.

        Catches torn copies, truncation, and tampering BEFORE the
        checkpoint reaches a mesh; a failed verify must gate any swap."""
        manifest = self.get(version)
        root = manifest.checkpoint_path
        problems: list[str] = []
        for rel, meta in sorted(manifest.files.items()):
            path = root / rel
            if not path.is_file():
                problems.append(f"missing file {rel}")
                continue
            digest, size = _file_digest(path)
            if size != meta["bytes"]:
                problems.append(
                    f"{rel}: {size} bytes, manifest says {meta['bytes']}"
                )
            elif digest != meta["sha256"]:
                problems.append(f"{rel}: content digest mismatch")
        on_disk = {
            str(p.relative_to(root))
            for p in root.rglob("*")
            if p.is_file()
        }
        for extra in sorted(on_disk - set(manifest.files)):
            problems.append(f"unmanifested file {extra}")
        return (not problems), problems

    def fsck(self) -> dict[int, list[str]]:
        """verify() every version; returns {version: problems} (empty list
        = clean). The `cli rollout fsck` surface."""
        return {v: self.verify(v)[1] for v in self.versions()}

    # ----------------------------------------------------------- retention
    def retain(self, keep_last: int, pinned: "Iterable[int]" = ()) -> list[int]:
        """Delete all but the newest `keep_last` versions. The active
        version and the active version's parent (the rollback target) are
        always kept regardless, as is every version in `pinned` — the
        caller-supplied protection set for versions the keep-last window
        cannot see are still referenced: an OPEN canary candidate
        (CanaryController.pinned_versions — mid burn-in its version may
        be neither active nor newest) and checkpoints an incident corpus
        mined against (learn/miner.IncidentCorpus.lineage_versions —
        deleting them orphans the corpus's provenance and any trace
        replay that resolves it). Returns the deleted version ids."""
        if keep_last < 1:
            return []
        versions = self.versions()
        keep = set(versions[-keep_last:])
        keep.update(int(v) for v in pinned)
        active = self.active()
        if active is not None:
            keep.add(active)
            try:
                parent = self.get(active).parent
            except RegistryError:
                parent = None
            if parent is not None:
                keep.add(parent)
        deleted = []
        for v in versions:
            if v in keep:
                continue
            shutil.rmtree(self._version_dir(v), ignore_errors=True)
            deleted.append(v)
        if deleted:
            logger.info("retention deleted versions %s", deleted)
        return deleted

    # --------------------------------------------------------------- misc
    def status(self) -> dict:
        """JSON-ready summary for `cli rollout status` and /metrics."""
        versions = []
        for v in self.versions():
            m = self.get(v)
            versions.append({
                "version": v,
                "config": m.config_name,
                "fingerprint": m.config_fingerprint,
                "parent": m.parent,
                "scores": m.scores,
                "n_files": len(m.files),
                "bytes": sum(f["bytes"] for f in m.files.values()),
                "note": m.note,
            })
        return {
            "root": str(self.root),
            "active": self.active(),
            "versions": versions,
        }
