"""Shadow arm: mirror live decisions through a candidate, score both.

A candidate checkpoint that passed offline distillation eval still hasn't
seen LIVE traffic — real pod shapes, real snapshot drift. Before the canary
gate ever promotes it, the shadow arm builds evidence for free: a
configurable fraction of `schedule_pod` decisions (sched/loop.py) is
mirrored — NON-BINDING, off the hot path — through the candidate backend,
and both answers are scored against a stateless spread-teacher reference:

- agreement: candidate node == incumbent node;
- teacher agreement for each arm (the one-step spread-lookahead pick,
  the same objective sim/teacher.py optimizes — stateless here because
  live traffic owns the real placements);
- projected-spread delta: spread-after-placement(candidate) minus
  spread-after-placement(incumbent) — negative means the candidate's
  choices leave the cluster better balanced.

Hot-path cost is one counter check and one executor submit (the same
pool pattern the replica prewarm reply path uses — the watch loop never
waits on a candidate decode). Backpressure drops mirrors instead of
queueing unbounded: shadow data is a sample, not a ledger.
"""

from __future__ import annotations

import logging
import statistics
import threading
from collections.abc import Sequence
from concurrent.futures import ThreadPoolExecutor

from k8s_llm_scheduler_tpu.core.fallback import score_resource_balanced
from k8s_llm_scheduler_tpu.core.validation import feasible_nodes
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec, SchedulingDecision

logger = logging.getLogger(__name__)


def projected_spread(nodes: Sequence[NodeMetrics], chosen: str) -> float:
    """pstdev of fractional pod fills AFTER placing one pod on `chosen` —
    the spread-after metric the teacher's lookahead minimizes."""
    fills = []
    for n in nodes:
        if not n.max_pods:
            continue
        count = n.pod_count + (1 if n.name == chosen else 0)
        fills.append(count / n.max_pods)
    return statistics.pstdev(fills) if len(fills) > 1 else 0.0


def teacher_pick(pod: PodSpec, nodes: Sequence[NodeMetrics]) -> str | None:
    """Stateless one-step spread-lookahead reference choice (sim/teacher.py
    without the cross-wave memory — live traffic owns real placements, so
    only the snapshot-projected future is comparable)."""
    candidates = feasible_nodes(pod, nodes)
    candidates = [n for n in candidates if n.pod_count < n.max_pods or not n.max_pods]
    if not candidates:
        return None
    return min(
        candidates,
        key=lambda n: (
            round(projected_spread(nodes, n.name), 9),
            -score_resource_balanced(n),
            n.name,
        ),
    ).name


class ShadowScorer:
    """Mirror a fraction of live decisions through `candidate`, accumulate
    agreement/score deltas per candidate version. Attach to a Scheduler
    (scheduler.shadow = scorer); its stats surface through get_stats ->
    /metrics."""

    def __init__(
        self,
        candidate,                     # DecisionBackend
        *,
        fraction: float = 0.05,
        candidate_version: int | str | None = None,
        max_pending: int = 64,
        workers: int = 1,
    ) -> None:
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"shadow fraction must be in [0, 1], got {fraction}")
        self.candidate = candidate
        self.fraction = float(fraction)
        self.candidate_version = candidate_version
        self.max_pending = int(max_pending)
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="shadow"
        )
        self._lock = threading.Lock()
        self._seen = 0
        self._pending = 0
        self._closed = False
        self._counts = {
            "mirrored": 0,
            "agree": 0,
            "teacher_agree_incumbent": 0,
            "teacher_agree_candidate": 0,
            "errors": 0,
            "dropped": 0,
        }
        self._spread_delta_sum = 0.0

    # -------------------------------------------------------------- intake
    def observe(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics],
        decision: SchedulingDecision,
    ) -> bool:
        """Hot-path hook: maybe enqueue a mirror for this decision.
        Deterministic counter-based sampling (no RNG on the hot path, and
        a given fraction mirrors exactly that share of traffic). Returns
        True when a mirror was enqueued."""
        if self._closed or self.fraction <= 0.0 or decision is None:
            return False
        with self._lock:
            self._seen += 1
            take = int(self._seen * self.fraction) > int(
                (self._seen - 1) * self.fraction
            )
            if not take:
                return False
            if self._pending >= self.max_pending:
                self._counts["dropped"] += 1
                return False
            self._pending += 1
        try:
            self._pool.submit(self._mirror, pod, nodes, decision.selected_node)
        except RuntimeError:  # pool shut down under us
            with self._lock:
                self._pending -= 1
            return False
        return True

    # ------------------------------------------------------------- scoring
    def _mirror(self, pod, nodes, incumbent_node: str) -> None:
        try:
            cand = self.candidate.get_scheduling_decision(pod, nodes)
            cand_node = cand.selected_node
        except Exception:
            with self._lock:
                self._pending -= 1
                self._counts["errors"] += 1
            return
        ref = teacher_pick(pod, nodes)
        delta = (
            projected_spread(nodes, cand_node)
            - projected_spread(nodes, incumbent_node)
        )
        with self._lock:
            self._pending -= 1
            self._counts["mirrored"] += 1
            if cand_node == incumbent_node:
                self._counts["agree"] += 1
            if ref is not None:
                if incumbent_node == ref:
                    self._counts["teacher_agree_incumbent"] += 1
                if cand_node == ref:
                    self._counts["teacher_agree_candidate"] += 1
            self._spread_delta_sum += delta

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            n = self._counts["mirrored"]
            out = {
                "fraction": self.fraction,
                "seen": self._seen,
                "pending": self._pending,
                **self._counts,
                "agree_frac": round(self._counts["agree"] / n, 4) if n else None,
                "teacher_agree_incumbent_frac": (
                    round(self._counts["teacher_agree_incumbent"] / n, 4)
                    if n else None
                ),
                "teacher_agree_candidate_frac": (
                    round(self._counts["teacher_agree_candidate"] / n, 4)
                    if n else None
                ),
                "spread_delta_mean": (
                    round(self._spread_delta_sum / n, 6) if n else None
                ),
            }
            if self.candidate_version is not None:
                out["candidate_version"] = self.candidate_version
            return out

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait for in-flight mirrors to land (tests / orderly shutdown)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            time.sleep(0.005)  # graftlint: ok[raw-clock] — bounded drain poll on the scorer pool's own background thread
        return False

    def close(self) -> None:
        self._closed = True
        self._pool.shutdown(wait=False, cancel_futures=True)
