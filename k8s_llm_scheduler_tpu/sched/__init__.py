"""The scheduling control plane: decision client, watch loop, stats."""
