"""DecisionClient — resilience wrapper around any DecisionBackend.

Control-flow parity with the reference's HuggingFaceClient.get_scheduling_decision
(reference scheduler.py:377-416): cache check first (scheduler.py:380-385);
up to max_retries attempts through the circuit breaker (scheduler.py:390-395)
with exponential backoff retry_delay * 2**attempt (scheduler.py:409-412 —
the reference hardcodes base 1s and never reads its retry_delay config key;
here the key is live); breaker-open or retry exhaustion falls back to the
heuristic scorer (scheduler.py:404-416); successful non-fallback decisions
are cached (scheduler.py:398-399); decisions are validated against the live
node list before acceptance (scheduler.py:453-465).

Differences, on purpose:
- genuinely async: backoff is `await asyncio.sleep`, the backend call runs in
  a worker thread — the reference's `time.sleep` blocks its event loop
  (SURVEY §2 component 12);
- the breaker guards the in-tree TPU engine (BackendError, XLA failures)
  instead of a remote HTTP API;
- stats parity: total/successful/failed/cached requests, avg response time,
  breaker trips (scheduler.py:344-351).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.core.breaker import CircuitBreaker, CircuitOpenError
from k8s_llm_scheduler_tpu.observability import spans
from k8s_llm_scheduler_tpu.sched import deadline
from k8s_llm_scheduler_tpu.sched.deadline import (
    LADDER,
    DeadlineBudget,
    DeadlineExceededError,
)
from k8s_llm_scheduler_tpu.core.cache import DecisionCache, decision_cache_key
from k8s_llm_scheduler_tpu.core.fallback import fallback_decision
from k8s_llm_scheduler_tpu.core.validation import validate_decision
from k8s_llm_scheduler_tpu.engine.backend import DecisionBackend, NoFeasibleNodeError
from k8s_llm_scheduler_tpu.types import (
    DecisionSource,
    NodeMetrics,
    PodSpec,
    SchedulingDecision,
)

logger = logging.getLogger(__name__)


class DecisionClient:
    def __init__(
        self,
        backend: DecisionBackend,
        cache: DecisionCache | None = None,
        breaker: CircuitBreaker | None = None,
        max_retries: int = 3,
        retry_delay: float = 1.0,
        fallback_strategy: str = "resource_balanced",
        fallback_enabled: bool = True,
        deadline_ms: float | None = None,
        llm_min_budget_ms: float = 25.0,
    ) -> None:
        self.backend = backend
        self.cache = cache
        self.breaker = breaker
        if breaker is not None:
            # Unschedulable pods must never open the circuit (pod property,
            # not device health); neither must a deadline rejection (an
            # overloaded CALLER is not a sick device).
            for exc_type in (NoFeasibleNodeError, DeadlineExceededError):
                if exc_type not in breaker.non_failure_exceptions:
                    breaker.non_failure_exceptions = (
                        *breaker.non_failure_exceptions,
                        exc_type,
                    )
        self.max_retries = max(1, int(max_retries))
        self.retry_delay = float(retry_delay)
        self.fallback_strategy = fallback_strategy
        self.fallback_enabled = fallback_enabled
        # Deadline-budgeted degradation (sched/deadline.py): every
        # decision gets `deadline_ms` of budget (None = unlimited) and
        # the ladder LLM -> cached -> heuristic is stepped by what
        # remains: below `llm_min_budget_ms` the model rung is no longer
        # affordable and the decision sheds to a fast answer instead of
        # timing out its bind. An SLO burn-rate brownout (enter_brownout,
        # wired to observability/slo.py on_trip in `cli run`) forces the
        # shed regardless of budget.
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.llm_min_budget_ms = float(llm_min_budget_ms)
        self._brownout: set[str] = set()
        self.stats = {
            "total_requests": 0,
            "successful_requests": 0,
            "failed_requests": 0,
            "cached_requests": 0,
            "coalesced_requests": 0,
            "fallback_decisions": 0,
            "invalid_decisions": 0,
            "degraded_decisions": 0,
            "degraded_fallbacks": 0,
            "brownout_decisions": 0,
            "deadline_timeouts": 0,
            "avg_response_time_ms": 0.0,
        }
        # Single-flight: identical (pod shape, cluster state) decisions share
        # one in-flight backend call — without this, a 1000-pod burst of 8
        # shapes fires 1000 LLM requests before the first one can populate
        # the cache.
        self._inflight: dict[str, asyncio.Future] = {}

    def _note_response_time(self, ms: float) -> None:
        """Running average (reference scheduler.py:435-441)."""
        n = self.stats["successful_requests"]
        prev = self.stats["avg_response_time_ms"]
        self.stats["avg_response_time_ms"] = prev + (ms - prev) / max(1, n)

    def _call_backend(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        if self.breaker is not None:
            return self.breaker.call(self.backend.get_scheduling_decision, pod, nodes)
        return self.backend.get_scheduling_decision(pod, nodes)

    async def _call_backend_async(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        """Prefer the backend's natively-async path (no worker thread held
        per in-flight decision — a burst of N distinct pod shapes would pin
        N pool threads for a full wave round trip otherwise); fall back to
        asyncio.to_thread for sync-only backends (fakes, stubs)."""
        afn = getattr(self.backend, "get_scheduling_decision_async", None)
        if afn is not None:
            if self.breaker is not None:
                return await self.breaker.async_call(afn, pod, nodes)
            return await afn(pod, nodes)
        return await asyncio.to_thread(self._call_backend, pod, nodes)

    def _fallback(
        self, nodes: Sequence[NodeMetrics], reason: str, pod: PodSpec | None = None
    ) -> SchedulingDecision | None:
        trace = spans.current_trace()
        if trace is not None:
            trace.set_meta(fallback_reason=reason)
        if not self.fallback_enabled:
            return None
        decision = fallback_decision(
            nodes, reason=reason, strategy=self.fallback_strategy, pod=pod
        )
        if decision is not None:
            self.stats["fallback_decisions"] += 1
        return decision

    # ---------------------------------------------------------- degradation
    def enter_brownout(self, reason: str = "manual") -> None:
        """SLO burn-rate brownout: shed the LLM rung for every decision
        until the burn clears (exit_brownout). Reasons are a SET — two
        burning objectives require two clears."""
        self._brownout.add(reason)
        logger.warning("decision brownout entered (%s)", reason)

    def exit_brownout(self, reason: str = "manual") -> None:
        if reason not in self._brownout:
            return  # already clear (or never entered): nothing to log
        self._brownout.discard(reason)
        if not self._brownout:
            logger.info("decision brownout cleared (%s)", reason)

    @property
    def brownout(self) -> bool:
        return bool(self._brownout)

    def _degrade(
        self,
        nodes: Sequence[NodeMetrics],
        reason: str,
        pod: PodSpec | None,
        rung: str = LADDER[-1],
    ) -> SchedulingDecision | None:
        """Step down the ladder (sched/deadline.LADDER): the cached rung
        was already consulted upstream (it is free and always first), so
        a degradation here lands on the heuristic floor. Counted apart
        from ordinary fallbacks — `degraded_decisions` is the ladder's
        engagement meter (bench --preset chaos asserts it moves in the
        brownout regime)."""
        self.stats["degraded_decisions"] += 1
        trace = spans.current_trace()
        if trace is not None:
            trace.set_meta(degraded=rung, degraded_reason=reason)
        decision = self._fallback(nodes, reason, pod)
        if decision is not None:
            # degrades that actually produced a fallback decision — the
            # counter rollout/canary subtracts from the scheduler-side
            # fallback count (a shed with fallback disabled or no
            # feasible node lands in `unschedulable`, not `fallback`,
            # and must not be subtracted)
            self.stats["degraded_fallbacks"] += 1
        return decision

    def fast_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> tuple[SchedulingDecision | None, "asyncio.Future | None"]:
        """Synchronous fast path for the burst hot loop (sched/loop.py):

        - (decision, None): cache hit, counted, ready to bind — no
          coroutine needed;
        - (None, future): a single-flight leader for this key is in flight;
          the caller may park the pod on the future (follower fan-out) and
          bind the whole batch when it resolves — count via
          note_coalesced(n) at flush;
        - (None, None): backend work needed — take the full async path.
        """
        if self.cache is None:
            return None, None
        key = decision_cache_key(pod, nodes)
        cached = self.cache.get(pod, nodes, key=key)
        if cached is not None:
            self.stats["total_requests"] += 1
            self.stats["cached_requests"] += 1
            return dataclasses.replace(cached, source=DecisionSource.CACHE), None
        return None, self._inflight.get(key)

    def note_coalesced(self, n: int) -> None:
        """Account a flushed follower batch (see fast_decision)."""
        self.stats["total_requests"] += n
        self.stats["coalesced_requests"] += n
        self.stats["cached_requests"] += n

    async def get_scheduling_decision(
        self,
        pod: PodSpec,
        nodes: Sequence[NodeMetrics],
        concurrency: "asyncio.Semaphore | None" = None,
    ) -> SchedulingDecision | None:
        """Decide a node for `pod`, or None when nothing can decide (the pod
        stays Pending and will be re-observed — correctness rests on the
        cluster as source of truth, SURVEY §5 checkpoint note).

        `concurrency` bounds ONLY the backend-work path (_decide_uncached):
        cache hits and single-flight follower waits never hold a slot — but
        a follower that falls through after a failed leader does, so a
        leader failure can't stampede an unbounded herd onto the backend."""
        self.stats["total_requests"] += 1
        # Deadline budget: adopt the ambient one (a caller that already
        # started the clock — e.g. a replica server re-installing a wire
        # deadline) or start this decision's own. Started HERE, before the
        # cache lookup, so the budget covers the decision end to end.
        budget = deadline.current_budget()
        if budget is None and self.deadline_ms is not None:
            budget = DeadlineBudget.start(self.deadline_ms)

        key: str | None = None
        generation: int | None = None
        my_future: asyncio.Future | None = None
        if self.cache is not None:
            # Staleness is handled by the cache key itself: node names and
            # readiness are part of the digest (core/cache.py), so a node
            # going NotReady or disappearing changes the key and misses.
            # The policy epoch is captured HERE, before the backend call: a
            # decision computed under pre-swap weights that resolves after
            # a hot swap's bump_generation must file under the OLD epoch
            # (unreachable), not the new one (rollout/hotswap.py).
            key = decision_cache_key(pod, nodes)
            generation = self.cache.generation
            trace = spans.current_trace()
            if trace is not None:
                # prompt/decision identity for the flight recorder: the
                # cache key digests (pod shape, cluster snapshot) — the
                # same equivalence class the prompt prefix is keyed by
                trace.set_meta(cache_key=key[:16], cache_generation=generation)
            cached = self.cache.get(pod, nodes, key=key)
            if trace is not None:
                # which tier answered (or "miss"): l1_hit / l2_hit come
                # from the cache's thread-local lookup record — the fleet
                # tiering attribute (fleet/cache.TieredDecisionCache); a
                # flat DecisionCache reports l1_hit/miss.
                tier = getattr(self.cache, "last_tier", None)
                if tier is not None:
                    trace.set_meta(cache_tier=tier)
            if cached is not None:
                self.stats["cached_requests"] += 1
                return dataclasses.replace(cached, source=DecisionSource.CACHE)
            existing = self._inflight.get(key)
            if existing is not None:
                with spans.span("coalesce_wait"):
                    try:
                        leader = await asyncio.shield(existing)
                    except Exception:
                        leader = None
                if leader is not None:
                    self.stats["coalesced_requests"] += 1
                    self.stats["cached_requests"] += 1
                    if trace is not None:
                        trace.set_meta(cache_tier="coalesced")
                    return dataclasses.replace(leader, source=DecisionSource.CACHE)
                # Leader failed or fell back — compute independently below.
            fut = asyncio.get_running_loop().create_future()
            # Register only if nobody else re-registered first (two followers
            # waking from a failed leader must not overwrite each other).
            if self._inflight.setdefault(key, fut) is fut:
                my_future = fut

        try:
            if concurrency is not None:
                async with concurrency:
                    decision = await self._decide_uncached(
                        pod, nodes, cache_key=key, generation=generation,
                        budget=budget,
                    )
            else:
                decision = await self._decide_uncached(
                    pod, nodes, cache_key=key, generation=generation,
                    budget=budget,
                )
        except BaseException:
            if my_future is not None:
                if self._inflight.get(key) is my_future:
                    del self._inflight[key]
                my_future.set_result(None)
            raise
        if my_future is not None:
            if self._inflight.get(key) is my_future:
                del self._inflight[key]
            # Followers reuse only clean LLM decisions.
            my_future.set_result(
                decision if decision is not None and not decision.fallback_needed else None
            )
        return decision

    async def _decide_uncached(
        self,
        pod: PodSpec,
        nodes: Sequence[NodeMetrics],
        cache_key: str | None = None,
        generation: int | None = None,
        budget: DeadlineBudget | None = None,
    ) -> SchedulingDecision | None:
        # Degradation ladder gate (LLM rung affordability). Brownout
        # first: a burning SLO says the backend's latency is ALREADY
        # hurting the fleet — keep even affordable decisions off it.
        if self._brownout:
            self.stats["brownout_decisions"] += 1
            return self._degrade(
                nodes, f"brownout:{','.join(sorted(self._brownout))}", pod
            )
        if budget is not None and budget.remaining_ms() < self.llm_min_budget_ms:
            return self._degrade(nodes, "deadline_budget", pod)

        last_error: Exception | None = None
        for attempt in range(self.max_retries):
            start = time.perf_counter()  # per attempt: excludes backoff sleeps
            try:
                with spans.span("backend", attempt=attempt):
                    if budget is None:
                        decision = await self._call_backend_async(pod, nodes)
                    else:
                        # the ambient install lets the replica wire stamp
                        # the REMAINING budget onto the decision frame;
                        # wait_for is the local enforcement of the same
                        # deadline (sheds to a fast decision instead of
                        # letting the bind time out)
                        with deadline.running(budget):
                            decision = await asyncio.wait_for(
                                self._call_backend_async(pod, nodes),
                                timeout=max(budget.remaining_ms(), 1.0) / 1000.0,
                            )
            except asyncio.TimeoutError:
                self.stats["deadline_timeouts"] += 1
                logger.warning(
                    "decision for %s/%s exceeded its %.0fms deadline budget, "
                    "degrading", pod.namespace, pod.name,
                    budget.total_ms if budget is not None else 0.0,
                )
                return self._degrade(nodes, "deadline_exceeded", pod)
            except DeadlineExceededError:
                # the remote end refused an already-expired frame: same
                # shed, minus a wave of wasted compute on the worker
                self.stats["deadline_timeouts"] += 1
                return self._degrade(nodes, "deadline_exceeded", pod)
            except CircuitOpenError as exc:
                logger.warning("circuit open, using fallback: %s", exc)
                return self._fallback(nodes, "circuit_open", pod)
            except NoFeasibleNodeError as exc:
                # Pod property, not backend health: no retries, no breaker
                # failure, no constraint-ignoring fallback. Pod stays Pending.
                logger.warning("unschedulable: %s", exc)
                return self._fallback(nodes, "no_feasible_node", pod)
            except Exception as exc:
                last_error = exc
                logger.warning(
                    "backend attempt %d/%d failed: %s", attempt + 1, self.max_retries, exc
                )
                if budget is not None and (
                    budget.remaining_ms() < self.llm_min_budget_ms
                ):
                    # a retry the budget can't afford is a disguised
                    # timeout — shed now, with the error on record
                    return self._degrade(
                        nodes, f"deadline_budget:{last_error}", pod
                    )
                if attempt + 1 < self.max_retries:
                    backoff = self.retry_delay * (2**attempt)
                    if budget is not None:
                        backoff = min(
                            backoff, max(budget.remaining_ms(), 0.0) / 1000.0
                        )
                    await asyncio.sleep(backoff)
                continue

            if not validate_decision(decision, nodes):
                # Hallucinated node name — defense in depth behind the
                # constrained decoder (reference scheduler.py:453-465).
                self.stats["invalid_decisions"] += 1
                logger.warning(
                    "backend selected unknown node %r, using fallback",
                    decision.selected_node,
                )
                return self._fallback(nodes, "invalid_node", pod)

            self.stats["successful_requests"] += 1
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            if decision.latency_ms == 0.0:
                decision.latency_ms = elapsed_ms
            self._note_response_time(elapsed_ms)
            if self.cache is not None:
                self.cache.set(
                    pod, nodes, decision, key=cache_key, generation=generation
                )
            return decision

        self.stats["failed_requests"] += 1
        logger.warning("all %d attempts failed (%s), using fallback", self.max_retries, last_error)
        return self._fallback(nodes, f"retries_exhausted:{last_error}", pod)

    def prewarm_prefix(self, nodes):
        """Forward an advisory prefix prewarm to the backend (see
        engine/local.prewarm_prefix). Returns the backend's Future, or
        None when the backend doesn't support prewarming (stub/remote
        backends) — the caller disables its prewarm loop on None."""
        fn = getattr(self.backend, "prewarm_prefix", None)
        return None if fn is None else fn(nodes)

    def get_stats(self) -> dict:
        out = dict(self.stats)
        if self._brownout:
            out["brownout"] = sorted(self._brownout)
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        if self.breaker is not None:
            out["circuit_breaker"] = self.breaker.stats()
        backend_stats = getattr(self.backend, "get_stats", None)
        if backend_stats is not None:
            # engine-level counters (waves, prefix hits, decode tokens, ...)
            # surface through /metrics alongside the scheduling stats
            out["engine"] = backend_stats()
        return out
