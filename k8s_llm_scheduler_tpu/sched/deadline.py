"""Per-decision deadline budgets — the currency of graceful degradation.

A scheduling decision is only worth computing while someone is still
waiting for it: a bind that lands after the pod's effective deadline is
indistinguishable from a failed bind to the workload, and a backend that
is 10x slow turns a burst into a pile-up of decisions nobody can use.
This module gives every decision a BUDGET that rides with it through the
whole pipeline:

- `DeadlineBudget` is a start-time + total-ms record; `remaining_ms()`
  is the only question anyone asks it.
- The budget propagates AMBIENTLY via a contextvar (same discipline as
  observability/spans): `running(budget)` installs it for a scope,
  `current_budget()` reads it anywhere downstream — including the
  replica wire client, which stamps the REMAINING budget onto the
  decision frame (`deadline_ms`); the worker server restarts a budget
  from that remainder (wire transit has already been spent by the
  sender) and re-installs it around its backend call. An already-expired
  frame is refused with a typed `DeadlineExceededError` instead of
  burning a wave on a dead decision.
- `DecisionClient` (sched/client.py) steps a degradation LADDER by the
  remaining budget: full LLM decision while the budget affords one,
  cached decision when one exists (always consulted first — it is free),
  heuristic fallback when the budget (or an SLO brownout) says the model
  rung is no longer affordable. Shedding beats timing out: an overloaded
  backend degrades decision QUALITY, never decision delivery.

Clock discipline: budgets are judged on an injectable monotonic clock so
chaos/virtual-time tests can reason about expiry without sleeping.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import time
from typing import Callable, Iterator

# Degradation ladder rungs, best to cheapest. The ladder is stepped by
# remaining budget and by SLO brownout (sched/client.py); the rung that
# answered is stamped on the decision trace as `degraded` meta.
LADDER = ("llm", "cached", "heuristic")


class DeadlineExceededError(RuntimeError):
    """A decision's budget expired before (or while) the backend could
    serve it. NOT a backend-health failure — the breaker must not count
    it (an overloaded caller is not a sick device), and the client
    degrades to the next ladder rung instead of retrying."""


@dataclasses.dataclass
class DeadlineBudget:
    """One decision's time allowance. `started` is a reading of `clock`
    (monotonic); all judgments are deltas against it."""

    total_ms: float
    started: float
    clock: Callable[[], float] = time.monotonic

    @classmethod
    def start(
        cls, total_ms: float, clock: Callable[[], float] = time.monotonic
    ) -> "DeadlineBudget":
        return cls(total_ms=float(total_ms), started=clock(), clock=clock)

    def remaining_ms(self) -> float:
        return self.total_ms - (self.clock() - self.started) * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining_ms() <= 0.0


_current: contextvars.ContextVar[DeadlineBudget | None] = contextvars.ContextVar(
    "decision_deadline_budget", default=None
)


def current_budget() -> DeadlineBudget | None:
    """The ambient budget, if any scope installed one."""
    return _current.get()


@contextlib.contextmanager
def running(budget: DeadlineBudget | None) -> Iterator[DeadlineBudget | None]:
    """Install `budget` as the ambient budget for the scope. None is
    allowed (and a no-op install) so callers can write one with-block
    whether or not a deadline is configured."""
    token = _current.set(budget)
    try:
        yield budget
    finally:
        _current.reset(token)


def remaining_ms() -> float | None:
    """Remaining ambient budget in ms, or None when no budget is set —
    the value the replica wire stamps on decision frames."""
    budget = _current.get()
    return None if budget is None else budget.remaining_ms()
