"""Snapshot-delta prompt encoding: O(changed) prompts over a pinned pin.

The whole-prompt scheme re-renders the full cluster state into every
burst's prefix — and because node USAGE figures drift with every bind,
consecutive snapshots' renders diverge a few characters into the first
drifted node, so the engine's LCP prefix reuse collapses and each burst
re-pays an O(cluster) prefill. At 10k nodes that is the cost that makes
per-decision LLM scheduling unaffordable (ROADMAP item 2).

The delta encoding fixes the RENDERING, which fixes the prefill: the
first snapshot is PINNED (rendered once, its token prefix KV pinned on
device — engine/admission/pinned.py), and every later snapshot renders as

    <pinned snapshot, verbatim>  +  STATE UPDATES section (changed nodes
    only, latest values win)     +  per-pod suffix

so the pinned text is a literal string prefix of every subsequent prompt
— causal attention makes its KV bit-reusable — and prefill cost scales
with HOW MUCH CHANGED, not cluster size. The model sees the same
information (full state + overriding updates); the decision grammar and
all validation still run against the LIVE node list.

Re-pin policy: membership or readiness-set changes re-pin immediately
(the VALID NODE NAMES list and the decision grammar would otherwise
disagree with the pinned text), and a drift fraction above
`repin_fraction` re-pins because the delta section is approaching the
cost of a fresh render. Encoding is a pure function of (pin, snapshot)
between re-pins, so every pod of a burst — and the prewarm path — lands
on one group key.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.core.prompt import cluster_prefix, render_node_block
from k8s_llm_scheduler_tpu.types import NodeMetrics

DELTA_HEADER = (
    "STATE UPDATES (changes since the snapshot above; latest values win):"
)


@dataclasses.dataclass(frozen=True)
class DeltaPrompt:
    """One encoded cluster part, ready to glue a pod suffix onto."""

    cluster_part: str     # full prefix text for this decision
    pin_key: str | None   # stable id of the pinned snapshot (replica-local)
    pin_text: str         # the pinned snapshot's own prefix text
    delta_nodes: int      # nodes rendered in the delta section (0 = none)
    repinned: bool        # this encode re-pinned (fresh full render)
    # Content digest of pin_text. pin_key is a replica-local sequence
    # number ("pin-3") — two replicas watching the same cluster number
    # their pins independently, but their pin TEXT (hence tokens, hence
    # prefix KV) is identical. The shared prefix-KV plane keys pages by
    # content, and this digest is the cross-replica rendezvous for it.
    pin_digest: str = ""


@dataclasses.dataclass
class _Pin:
    key: str
    names: tuple[str, ...]          # node order at pin time
    ready: tuple[bool, ...]         # readiness at pin time
    blocks: dict[str, str]          # name -> rendered node block
    text: str                       # full pinned cluster part
    digest: str                     # blake2b(text) — fleet-sharable id


def pin_text_digest(text: str) -> str:
    """Content address of a pinned snapshot render — identical across
    replicas that rendered the same cluster state (core/prompt.py renders
    deterministically), unlike the per-replica pin-<seq> keys."""
    return hashlib.blake2b(text.encode("utf-8"), digest_size=16).hexdigest()


class SnapshotDeltaEncoder:
    """Stateful per-backend encoder; thread-safe (decisions prepare from
    many caller threads)."""

    def __init__(self, repin_fraction: float = 0.25) -> None:
        self.repin_fraction = float(repin_fraction)
        self._lock = threading.Lock()
        self._pin: _Pin | None = None
        self._pin_seq = 0
        self.stats_counters = {
            "encodes": 0,
            "pins": 0,
            "delta_encodes": 0,
            "clean_encodes": 0,
            "repin_membership": 0,
            "repin_drift": 0,
            "delta_nodes_total": 0,
        }

    # ------------------------------------------------------------- public
    def encode(self, nodes: Sequence[NodeMetrics]) -> DeltaPrompt:
        with self._lock:
            self.stats_counters["encodes"] += 1
            names = tuple(n.name for n in nodes)
            ready = tuple(bool(n.is_ready) for n in nodes)
            pin = self._pin
            if pin is None or names != pin.names or ready != pin.ready:
                if pin is not None:
                    self.stats_counters["repin_membership"] += 1
                return self._repin_locked(nodes)
            blocks = {n.name: render_node_block(n) for n in nodes}
            changed = [n for n in names if blocks[n] != pin.blocks[n]]
            if not changed:
                self.stats_counters["clean_encodes"] += 1
                return DeltaPrompt(
                    cluster_part=pin.text, pin_key=pin.key,
                    pin_text=pin.text, delta_nodes=0, repinned=False,
                    pin_digest=pin.digest,
                )
            if len(changed) > self.repin_fraction * len(names):
                self.stats_counters["repin_drift"] += 1
                return self._repin_locked(nodes)
            delta = "\n\n".join(blocks[n] for n in changed)
            part = f"{pin.text}{DELTA_HEADER}\n\n{delta}\n\n"
            self.stats_counters["delta_encodes"] += 1
            self.stats_counters["delta_nodes_total"] += len(changed)
            return DeltaPrompt(
                cluster_part=part, pin_key=pin.key, pin_text=pin.text,
                delta_nodes=len(changed), repinned=False,
                pin_digest=pin.digest,
            )

    def reset(self) -> None:
        """Drop the pin (next encode re-pins fresh)."""
        with self._lock:
            self._pin = None

    def stats(self) -> dict:
        with self._lock:
            return dict(self.stats_counters)

    # ------------------------------------------------------------ internal
    def _repin_locked(self, nodes: Sequence[NodeMetrics]) -> DeltaPrompt:
        """Pin the current snapshot; the encoded part IS the plain full
        render (byte-identical to the non-delta path — zero drift means
        zero encoding overhead)."""
        self._pin_seq += 1
        # same trailing glue as PromptEngine.cluster_part: prefix + "\n"
        text = cluster_prefix(nodes) + "\n"
        pin = _Pin(
            key=f"pin-{self._pin_seq}",
            names=tuple(n.name for n in nodes),
            ready=tuple(bool(n.is_ready) for n in nodes),
            blocks={n.name: render_node_block(n) for n in nodes},
            text=text,
            digest=pin_text_digest(text),
        )
        self._pin = pin
        self.stats_counters["pins"] += 1
        return DeltaPrompt(
            cluster_part=text, pin_key=pin.key, pin_text=text,
            delta_nodes=0, repinned=True, pin_digest=pin.digest,
        )
