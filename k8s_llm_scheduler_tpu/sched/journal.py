"""Durable decision journal — the write-ahead log binds survive crashes by.

Every durable-state invariant the serving plane has (exactly-once binds,
no orphaned decisions, watch continuity) assumed until now that the
scheduler PROCESS survives: a crashed replica lost its in-flight pod
set, so a cold restart could re-decide a pod whose bind already landed
(the apiserver's 409 made that a wasted model call and a nondeterminism
source) or orphan a pod it decided but never bound. The journal records
the decision -> bind-intent -> bind-ack lifecycle per pod, the
informer's last-observed resourceVersion, and circuit-breaker trips, so
the recovery protocol (sched/recovery.py) can rebuild a replica from
disk and reconcile every open lifecycle against the cluster's actual
``pod.spec.nodeName`` instead of re-deciding.

On-disk format — append-only JSON-lines segments under one directory::

    <root>/seg-000001.log
    crc32hex {"k":"intent","ns":"default","name":"p0","node":"n3",...}\n

Each record line carries the crc32 of its JSON payload. Replay decodes
line by line and TRUNCATES at the first undecodable record (missing
newline, bad crc, bad JSON): a torn tail — the bytes a crash cut mid-
write — can never corrupt recovery, it only loses the record being
written at the instant of death, and the cluster reconciliation pass
re-derives that record's outcome anyway. Opening a journal physically
truncates the torn tail before appending (seeded-truncation fuzz in
tests/test_durable.py tears the last record at every byte boundary).

Durability policy (``fsync_policy``):

- ``"intent"`` (default): bind-intent records are flushed AND fsync'd
  BEFORE the bind leaves for the apiserver — the classic write-ahead
  property — while decide/ack/rv records ride the userspace buffer
  until the next intent sync (or close) carries them down. Losing a
  buffered record to a crash costs one cluster lookup at recovery,
  never a double bind or a lost pod: an unwritten ack leaves an open
  intent the reconciliation pass closes from ``pod.spec.nodeName``,
  and an unwritten decide means no bind was attempted — the watch
  re-offers the still-pending pod. The cluster is always the
  authority the journal is reconciled against.
- ``"always"``: every record flushed + fsync'd (the crash-harness
  setting — each kill point must observe exactly its own record set).
- ``"none"``: buffered until close/rotation (still torn-tail safe).

Segment rotation is the registry's proven discipline
(rollout/registry.py): when the active segment exceeds
``segment_max_records``, the LIVE state (open lifecycles, last rv, last
breaker snapshot) is compacted into a fresh segment written aside and
published with one ``os.replace``; old segments are deleted only after
the new one is durable, so a crash mid-rotation leaves either the old
segments or old+new (replay is idempotent over both), never neither.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import zlib
from pathlib import Path
from typing import Any, Iterator

logger = logging.getLogger(__name__)

_SEG_FMT = "seg-{:06d}.log"
_FSYNC_POLICIES = ("always", "intent", "none")


class JournalError(RuntimeError):
    """A journal operation failed (bad root, unknown fsync policy...)."""


def _encode(rec: dict) -> bytes:
    payload = json.dumps(rec, sort_keys=True, separators=(",", ":")).encode()
    return b"%08x " % (zlib.crc32(payload) & 0xFFFFFFFF) + payload + b"\n"


def _decode_line(line: bytes) -> dict | None:
    """One journal line -> record dict, or None when torn/corrupt."""
    if not line.endswith(b"\n") or len(line) < 10 or line[8:9] != b" ":
        return None
    payload = line[9:-1]
    try:
        if int(line[:8], 16) != (zlib.crc32(payload) & 0xFFFFFFFF):
            return None
        rec = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) and "k" in rec else None


@dataclasses.dataclass
class JournalState:
    """The live fold of a record stream: exactly what recovery needs.

    Completed lifecycles are pruned as their acks arrive (their outcome
    lives in ``acked``/counters), so the state — and therefore each
    compacted segment — stays proportional to the OPEN work, not the
    pod history."""

    # (ns, name) -> {"node": ...}: decide seen, no intent yet
    open_decisions: dict[tuple[str, str], dict] = dataclasses.field(
        default_factory=dict
    )
    # (ns, name) -> {"node", "shard", "epoch"}: intent seen, no ack
    open_intents: dict[tuple[str, str], dict] = dataclasses.field(
        default_factory=dict
    )
    # (ns, name) -> node for every ack with ok=True (the book
    # finalize_journal judges against the cluster)
    acked: dict[tuple[str, str], str] = dataclasses.field(
        default_factory=dict
    )
    last_rv: str | None = None
    breaker: dict | None = None
    counts: dict[str, int] = dataclasses.field(
        default_factory=lambda: {
            "records": 0, "decides": 0, "intents": 0,
            "acks_ok": 0, "acks_failed": 0, "drops": 0,
        }
    )

    def apply(self, rec: dict) -> None:
        kind = rec["k"]
        self.counts["records"] += 1
        if kind == "decide":
            key = (rec["ns"], rec["name"])
            self.counts["decides"] += 1
            self.open_decisions[key] = {"node": rec["node"]}
        elif kind == "intent":
            key = (rec["ns"], rec["name"])
            self.counts["intents"] += 1
            self.open_decisions.pop(key, None)
            self.open_intents[key] = {
                "node": rec["node"],
                "shard": rec.get("shard"),
                "epoch": rec.get("epoch"),
            }
        elif kind == "ack":
            key = (rec["ns"], rec["name"])
            self.open_decisions.pop(key, None)
            self.open_intents.pop(key, None)
            if rec.get("ok"):
                self.counts["acks_ok"] += 1
                self.acked[key] = rec["node"]
            else:
                self.counts["acks_failed"] += 1
        elif kind == "drop":
            key = (rec["ns"], rec["name"])
            self.counts["drops"] += 1
            self.open_decisions.pop(key, None)
            self.open_intents.pop(key, None)
        elif kind == "rv":
            self.last_rv = rec["rv"]
        elif kind == "breaker":
            self.breaker = dict(rec.get("snap") or {})
        # unknown kinds are skipped, not fatal: an older binary replaying
        # a newer journal must degrade to reconciliation, not crash

    def open_lifecycles(self) -> dict[tuple[str, str], dict]:
        """Everything recovery must reconcile: open intents (bind may or
        may not have landed) plus decisions that never reached an
        intent (the bind definitely did not land, but the decision is
        known — completing it needs no model call)."""
        return {**self.open_decisions, **self.open_intents}

    def snapshot_records(self) -> list[dict]:
        """The record stream that reconstructs this state exactly — what
        a compacted segment starts with."""
        out: list[dict] = []
        for (ns, name), rec in sorted(self.open_decisions.items()):
            out.append({"k": "decide", "ns": ns, "name": name,
                        "node": rec["node"]})
        for (ns, name), rec in sorted(self.open_intents.items()):
            out.append({"k": "decide", "ns": ns, "name": name,
                        "node": rec["node"]})
            out.append({"k": "intent", "ns": ns, "name": name,
                        "node": rec["node"], "shard": rec.get("shard"),
                        "epoch": rec.get("epoch")})
        # acked lifecycles are deliberately NOT snapshotted: compaction
        # exists to forget completed history (recovery never reads an
        # ack — the cluster is the authority on what landed), and
        # carrying them forward would make every rotation rewrite the
        # replica's whole bind history — O(lifetime) I/O per rotation
        # instead of O(open work)
        if self.last_rv is not None:
            out.append({"k": "rv", "rv": self.last_rv})
        if self.breaker is not None:
            out.append({"k": "breaker", "snap": dict(self.breaker)})
        return out


def _read_segment(path: Path) -> tuple[list[dict], int, int]:
    """(records, good_bytes, dropped_bytes) for one segment file.
    Decoding stops at the first torn/corrupt record — everything after a
    tear is unattributable (the tear may have eaten a record boundary)."""
    data = path.read_bytes()
    records: list[dict] = []
    offset = 0
    while offset < len(data):
        end = data.find(b"\n", offset)
        if end < 0:
            break  # torn tail: no newline
        line = data[offset:end + 1]
        rec = _decode_line(line)
        if rec is None:
            break  # corrupt record: stop here, drop the rest
        records.append(rec)
        offset = end + 1
    return records, offset, len(data) - offset


def _fsync_dir(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class DecisionJournal:
    """One replica's durable decision journal (module docstring).

    Thread-safe: binds journal from the event loop AND executor threads.
    The instance keeps the folded :class:`JournalState` current as it
    appends, so rotation compacts without a re-read and recovery starts
    from ``self.state`` the moment the journal opens."""

    def __init__(
        self,
        root: str | Path,
        *,
        fsync_policy: str = "intent",
        segment_max_records: int = 4096,
    ) -> None:
        if fsync_policy not in _FSYNC_POLICIES:
            raise JournalError(
                f"unknown fsync policy {fsync_policy!r} "
                f"(known: {_FSYNC_POLICIES})"
            )
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.fsync_policy = fsync_policy
        self.segment_max_records = int(segment_max_records)
        # single-writer guard: two live journals over one directory
        # would rotate each other's active segment out from underneath
        # (`cli journal compact` racing a running scheduler). flock is
        # advisory but both writers are this class; the lock dies with
        # the process, so a crashed holder never wedges recovery.
        self._lock_fd = os.open(
            self.root / ".lock", os.O_CREAT | os.O_RDWR, 0o644
        )
        try:
            import fcntl

            fcntl.flock(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(self._lock_fd)
            raise JournalError(
                f"journal {self.root} is held by a live writer (a "
                f"running scheduler?) — stop it before fsck/compact"
            ) from None
        self._lock = threading.Lock()
        self.state = JournalState()
        self.torn_bytes_dropped = 0
        self.appends = 0
        self.fsyncs = 0
        # sweep rotation debris (never a visible segment, always safe)
        for stale in self.root.glob(".staging-*"):
            stale.unlink(missing_ok=True)
        segments = self._segments()
        for i, seg in enumerate(segments):
            records, good, dropped = _read_segment(seg)
            for rec in records:
                self.state.apply(rec)
            if dropped:
                self.torn_bytes_dropped += dropped
                logger.warning(
                    "journal %s: dropped %d torn byte(s) from %s",
                    self.root, dropped, seg.name,
                )
                # crash-consistency: physically truncate the tear so new
                # appends never concatenate onto garbage
                with open(seg, "ab") as fh:
                    fh.truncate(good)
                    fh.flush()
                    os.fsync(fh.fileno())
        if segments:
            self._seg_path = segments[-1]
            self._seg_index = int(segments[-1].stem.split("-")[1])
            records, _good, _dropped = _read_segment(self._seg_path)
            self._seg_records = len(records)
        else:
            self._seg_index = 1
            self._seg_path = self.root / _SEG_FMT.format(1)
            self._seg_path.touch()
            self._seg_records = 0
        self._fh = open(self._seg_path, "ab")

    def _segments(self) -> list[Path]:
        return sorted(self.root.glob("seg-*.log"))

    # -------------------------------------------------------------- appends
    def _append(self, rec: dict, durable: bool) -> None:
        line = _encode(rec)
        with self._lock:
            fh = self._fh
            if fh is None:
                raise JournalError(f"journal {self.root} is closed")
            fh.write(line)
            if durable and self.fsync_policy != "none":
                # flush + fsync carry every buffered record down with
                # this one: after an intent sync, its decide (and any
                # earlier acks/rvs) are durable too
                fh.flush()
                os.fsync(fh.fileno())
                self.fsyncs += 1
            self.appends += 1
            self.state.apply(rec)
            self._seg_records += 1
            if self._seg_records >= self.segment_max_records:
                self._rotate_locked()

    def record_decide(self, namespace: str, name: str, node: str) -> None:
        self._append(
            {"k": "decide", "ns": namespace, "name": name, "node": node},
            durable=self.fsync_policy == "always",
        )

    def record_intent(
        self, namespace: str, name: str, node: str,
        shard: int | None = None, epoch: int | None = None,
    ) -> None:
        """THE write-ahead record: durable (under the default policy)
        before the bind leaves for the apiserver."""
        self._append(
            {"k": "intent", "ns": namespace, "name": name, "node": node,
             "shard": shard, "epoch": epoch},
            durable=self.fsync_policy in ("always", "intent"),
        )

    def record_ack(
        self, namespace: str, name: str, node: str, ok: bool,
        recovered: bool = False,
    ) -> None:
        self._append(
            {"k": "ack", "ns": namespace, "name": name, "node": node,
             "ok": bool(ok), "recovered": bool(recovered)},
            durable=self.fsync_policy == "always",
        )

    def record_drop(self, namespace: str, name: str, reason: str) -> None:
        """Close a lifecycle whose pod is GONE (deleted while we were
        down): nothing to bind, nothing to ack."""
        self._append(
            {"k": "drop", "ns": namespace, "name": name, "reason": reason},
            durable=self.fsync_policy == "always",
        )

    def record_rv(self, rv: str) -> None:
        """Informer watch position. Buffered under the default policy (a
        lost rv record widens the recovery relist by a few events, it
        can never lose a pod); "always" syncs it like everything else.
        De-duplicated: bookmark-heavy quiet streams must not grow the
        journal."""
        if self.state.last_rv == rv:
            return
        self._append(
            {"k": "rv", "rv": str(rv)},
            durable=self.fsync_policy == "always",
        )

    def record_breaker(self, snap: dict) -> None:
        """Breaker transition snapshot (core/breaker.py journal_sink): a
        rebooted replica restores OPEN with its remaining cooldown
        instead of hammering a backend the fleet knows is down. Synced
        like an intent (trips are rare and the whole point is surviving
        the crash that tends to FOLLOW a dying backend)."""
        self._append(
            {"k": "breaker", "snap": dict(snap)},
            durable=self.fsync_policy in ("always", "intent"),
        )

    # ------------------------------------------------------------- rotation
    def _rotate_locked(self) -> None:
        """Compact the live state into a fresh segment (write-aside +
        os.replace — rollout/registry.py discipline) and drop the old
        segments. Caller holds self._lock."""
        old_segments = self._segments()
        next_index = self._seg_index + 1
        final = self.root / _SEG_FMT.format(next_index)
        staging = self.root / f".staging-{final.name}"
        records = self.state.snapshot_records()
        with open(staging, "wb") as fh:
            for rec in records:
                fh.write(_encode(rec))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(staging, final)
        _fsync_dir(self.root)
        self._fh.close()
        self._fh = open(final, "ab")
        self._seg_path = final
        self._seg_index = next_index
        self._seg_records = len(records)
        # keep the in-memory fold consistent with what is now on disk:
        # the compacted segment no longer mentions acked lifecycles, so
        # the acked book resets to the post-rotation window (the chaos
        # monitor's finalize_journal judges that window — its runs never
        # rotate mid-flight)
        self.state.acked.clear()
        for seg in old_segments:
            if seg != final:
                seg.unlink(missing_ok=True)
        _fsync_dir(self.root)
        logger.info(
            "journal %s: compacted to %s (%d live record(s))",
            self.root, final.name, len(records),
        )

    def compact(self) -> dict:
        """Force a rotation now (the `cli journal compact` surface)."""
        with self._lock:
            before = self._seg_records
            self._rotate_locked()
            return {
                "segment": self._seg_path.name,
                "records_before": before,
                "records_after": self._seg_records,
            }

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None
            self._release_writer_lock_locked()

    def _release_writer_lock_locked(self) -> None:
        if self._lock_fd is not None:
            os.close(self._lock_fd)  # closing the fd drops the flock
            self._lock_fd = None

    def abandon(self) -> None:
        """Drop the file handle WITHOUT flushing — the simulated-crash
        teardown (chaos harness / tests). A real crash would not flush
        either; everything already flushed per append stays durable.
        The buffered bytes must be LOST, not written late: the fd is
        redirected to /dev/null before the handle is dropped, so the
        BufferedWriter's eventual GC flush lands harmlessly there
        instead of in whatever file has since reused the fd number."""
        with self._lock:
            fh = self._fh
            self._fh = None
            if fh is not None:
                devnull = os.open(os.devnull, os.O_WRONLY)
                try:
                    os.dup2(devnull, fh.fileno())
                finally:
                    os.close(devnull)
            self._release_writer_lock_locked()

    # ------------------------------------------------------------- tooling
    def stats(self) -> dict:
        with self._lock:
            return {
                "root": str(self.root),
                "segment": self._seg_path.name,
                "segment_records": self._seg_records,
                "appends": self.appends,
                "fsyncs": self.fsyncs,
                "fsync_policy": self.fsync_policy,
                "open_decisions": len(self.state.open_decisions),
                "open_intents": len(self.state.open_intents),
                "acked": len(self.state.acked),
                "last_rv": self.state.last_rv,
                "torn_bytes_dropped": self.torn_bytes_dropped,
                "counts": dict(self.state.counts),
            }


def replay(root: str | Path) -> JournalState:
    """Fold every segment under `root` into a JournalState without
    opening (or mutating) the journal — the read-only half of recovery
    and of `cli journal fsck`."""
    state = JournalState()
    for seg in sorted(Path(root).glob("seg-*.log")):
        records, _good, _dropped = _read_segment(seg)
        for rec in records:
            state.apply(rec)
    return state


def iter_records(root: str | Path) -> Iterator[tuple[str, dict]]:
    """(segment name, record) stream for `cli journal show`."""
    for seg in sorted(Path(root).glob("seg-*.log")):
        records, _good, _dropped = _read_segment(seg)
        for rec in records:
            yield seg.name, rec


def fsck(root: str | Path) -> dict:
    """Per-segment integrity report: record counts, torn bytes, and the
    folded end state. ok=True means every byte decodes (a torn tail is
    RECOVERABLE — replay truncates it — but fsck surfaces it so an
    operator knows a crash landed mid-write)."""
    root = Path(root)
    segments = []
    total_torn = 0
    state = JournalState()
    for seg in sorted(root.glob("seg-*.log")):
        records, good, dropped = _read_segment(seg)
        for rec in records:
            state.apply(rec)
        total_torn += dropped
        segments.append({
            "segment": seg.name,
            "records": len(records),
            "bytes": good,
            "torn_bytes": dropped,
        })
    return {
        "root": str(root),
        "ok": total_torn == 0,
        "segments": segments,
        "torn_bytes": total_torn,
        "open_decisions": len(state.open_decisions),
        "open_intents": len(state.open_intents),
        "acked": len(state.acked),
        "last_rv": state.last_rv,
        "counts": dict(state.counts),
    }
