"""The scheduling control loop.

Orchestration parity with the reference's CustomScheduler (reference
scheduler.py:625-770): watch pending pods filtered to our schedulerName
(scheduler.py:674-676), per pod snapshot node metrics → build spec → decide →
bind (scheduler.py:690-729), stats bookkeeping (scheduler.py:635-640), and
self-healing on stream errors with a backoff sleep (scheduler.py:683-685).

TPU-first differences:
- genuinely concurrent: each pending pod is scheduled as an asyncio task, so
  a burst of pods overlaps cluster snapshots with LLM decisions and the
  batching engine can coalesce their prompts; `max_concurrency` bounds the
  in-flight set. The reference processes one pod at a time
  (scheduler.py:704) and blocks its event loop.
- node-metrics snapshots are shared across a burst: a snapshot taken within
  `snapshot_ttl_s` is reused, both to cut API traffic and to keep the
  cluster-state prompt prefix identical across the burst (which is what lets
  the engine prefix-cache it on device).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.cluster.interface import (
    Binder,
    ClusterState,
    RawPod,
    raw_pod_to_spec,
)
from k8s_llm_scheduler_tpu.observability.trace import PhaseRecorder
from k8s_llm_scheduler_tpu.sched.client import DecisionClient
from k8s_llm_scheduler_tpu.types import DecisionSource, NodeMetrics

logger = logging.getLogger(__name__)


class Scheduler:
    def __init__(
        self,
        cluster: ClusterState,
        binder: Binder,
        client: DecisionClient,
        scheduler_name: str = "ai-llama-scheduler",
        max_concurrency: int = 64,
        snapshot_ttl_s: float = 1.0,
        error_backoff_s: float = 5.0,
    ) -> None:
        self.cluster = cluster
        self.binder = binder
        self.client = client
        self.scheduler_name = scheduler_name
        self.error_backoff_s = error_backoff_s
        self.snapshot_ttl_s = snapshot_ttl_s
        self._sem = asyncio.Semaphore(max_concurrency)
        self._snapshot: tuple[float, Sequence[NodeMetrics]] | None = None
        self._snapshot_lock = asyncio.Lock()
        self._tasks: set[asyncio.Task] = set()
        self._stop_event = asyncio.Event()
        self.running = False
        # Per-phase wall time of the decision pipeline (SURVEY §5 tracing:
        # the reference has none) — surfaces via get_stats and /metrics.
        self.phases = PhaseRecorder()
        self.stats = {
            "total_scheduled": 0,
            "llm_decisions": 0,
            "cache_decisions": 0,
            "fallback_decisions": 0,
            "failed_bindings": 0,
            "unschedulable": 0,
        }

    async def _node_snapshot(self) -> Sequence[NodeMetrics]:
        """Cluster snapshot, reused within snapshot_ttl_s across a burst."""
        async with self._snapshot_lock:
            now = time.monotonic()
            if self._snapshot is not None and now - self._snapshot[0] < self.snapshot_ttl_s:
                return self._snapshot[1]
            metrics = await asyncio.to_thread(self.cluster.get_node_metrics)
            self._snapshot = (time.monotonic(), metrics)
            return metrics

    async def schedule_pod(self, raw: RawPod) -> bool:
        """One pod through the full pipeline (reference scheduler.py:690-729).
        Returns True iff the pod was bound."""
        pod = raw_pod_to_spec(raw)
        with self.phases.phase("snapshot"):
            nodes = await self._node_snapshot()
        if not nodes:
            logger.warning("no nodes in cluster, leaving %s pending", pod.name)
            self.stats["unschedulable"] += 1
            return False

        with self.phases.phase("decide"):
            decision = await self.client.get_scheduling_decision(pod, nodes)
        if decision is None:
            self.stats["unschedulable"] += 1
            return False

        if decision.source is DecisionSource.FALLBACK:
            self.stats["fallback_decisions"] += 1
        elif decision.source is DecisionSource.CACHE:
            self.stats["cache_decisions"] += 1
        else:
            self.stats["llm_decisions"] += 1

        with self.phases.phase("bind"):
            if getattr(self.binder, "bind_is_nonblocking", False):
                # In-memory binders (FakeCluster) finish in microseconds; the
                # executor round trip would cost more than the bind and its
                # queue serializes a 1000-pod drain.
                ok = self.binder.bind_pod_to_node(
                    pod.name, pod.namespace, decision.selected_node
                )
            else:
                ok = await asyncio.to_thread(
                    self.binder.bind_pod_to_node,
                    pod.name, pod.namespace, decision.selected_node,
                )
        if not ok:
            self.stats["failed_bindings"] += 1
            logger.error(
                "binding failed: %s/%s -> %s", pod.namespace, pod.name, decision.selected_node
            )
            return False

        self.stats["total_scheduled"] += 1
        logger.info(
            "scheduled %s/%s -> %s (%s, conf=%.2f, %.1fms)",
            pod.namespace,
            pod.name,
            decision.selected_node,
            decision.source.value,
            decision.confidence,
            decision.latency_ms,
        )
        return True

    async def _spawn(self, raw: RawPod) -> None:
        async with self._sem:
            try:
                await self.schedule_pod(raw)
            except Exception:
                logger.exception("unhandled error scheduling %s/%s", raw.namespace, raw.name)

    async def run(self) -> None:
        """Watch loop: stream pending pods, schedule each concurrently.
        Self-heals on stream errors (reference scheduler.py:683-685).
        stop() terminates the loop even while the watch stream is idle —
        each stream read is raced against the stop event."""
        if self._stop_event.is_set():
            return  # stop() was called before run() got scheduled
        self.running = True
        # ONE long-lived stop-wait task raced against every stream read: a
        # fresh task per pod costs two task creations + a cancel on the
        # ingest hot path (~50 ms across a 1000-pod burst).
        stop_task = asyncio.ensure_future(self._stop_event.wait())
        try:
            while self.running:
                stream = None
                try:
                    stream = self.cluster.watch_pending_pods(self.scheduler_name).__aiter__()
                    while self.running:
                        next_task = asyncio.ensure_future(anext(stream))
                        done, _ = await asyncio.wait(
                            {next_task, stop_task}, return_when=asyncio.FIRST_COMPLETED
                        )
                        if stop_task in done and next_task not in done:
                            next_task.cancel()
                            try:
                                await next_task  # let the generator settle
                            except (asyncio.CancelledError, StopAsyncIteration):
                                pass
                            break
                        try:
                            raw = next_task.result()
                        except StopAsyncIteration:
                            break
                        task = asyncio.create_task(self._spawn(raw))
                        self._tasks.add(task)
                        task.add_done_callback(self._tasks.discard)
                    break  # stream ended cleanly or stop requested
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception(
                        "watch stream error, re-watching in %.1fs", self.error_backoff_s
                    )
                    await asyncio.sleep(self.error_backoff_s)
                finally:
                    if stream is not None and hasattr(stream, "aclose"):
                        # Run the generator's cleanup (stops kube watch threads).
                        await stream.aclose()
        finally:
            stop_task.cancel()
            try:
                await stop_task
            except asyncio.CancelledError:
                pass
        await self.drain()

    async def drain(self) -> None:
        """Wait for all in-flight scheduling tasks."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    def stop(self) -> None:
        """Request loop termination; safe to call before or during run()."""
        self.running = False
        self._stop_event.set()

    def get_stats(self) -> dict:
        return {
            **self.stats,
            "client": self.client.get_stats(),
            "phases": self.phases.snapshot(),
        }
