"""The scheduling control loop.

Orchestration parity with the reference's CustomScheduler (reference
scheduler.py:625-770): watch pending pods filtered to our schedulerName
(scheduler.py:674-676), per pod snapshot node metrics → build spec → decide →
bind (scheduler.py:690-729), stats bookkeeping (scheduler.py:635-640), and
self-healing on stream errors with a backoff sleep (scheduler.py:683-685).

TPU-first differences:
- genuinely concurrent: each pending pod is scheduled as an asyncio task, so
  a burst of pods overlaps cluster snapshots with LLM decisions and the
  batching engine can coalesce their prompts; `max_concurrency` bounds the
  in-flight set. The reference processes one pod at a time
  (scheduler.py:704) and blocks its event loop.
- node-metrics snapshots are shared across a burst: a snapshot taken within
  `snapshot_ttl_s` is reused, both to cut API traffic and to keep the
  cluster-state prompt prefix identical across the burst (which is what lets
  the engine prefix-cache it on device).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.cluster.interface import (
    Binder,
    ClusterState,
    RawPod,
    raw_pod_to_spec,
)
from k8s_llm_scheduler_tpu.observability import spans
from k8s_llm_scheduler_tpu.observability.trace import PhaseRecorder
from k8s_llm_scheduler_tpu.sched.client import DecisionClient
from k8s_llm_scheduler_tpu.types import DecisionSource, NodeMetrics

logger = logging.getLogger(__name__)


def _stamp_decision(trace, decision) -> None:
    """THE decision-metadata stamp (full, fast, and follower paths all
    converge here so /debug/decisions entries carry one field set)."""
    if trace is not None:
        # set_meta, never trace.meta[...]=: stamps race /debug handlers
        # serializing the trace from metrics-server threads
        trace.set_meta(
            source=decision.source.value,
            selected_node=decision.selected_node,
            confidence=decision.confidence,
        )


def _stamp_outcome(trace, outcome: str) -> None:
    if trace is not None:
        trace.set_meta(outcome=outcome)


class Scheduler:
    def __init__(
        self,
        cluster: ClusterState,
        binder: Binder,
        client: DecisionClient,
        scheduler_name: str = "ai-llama-scheduler",
        max_concurrency: int = 64,
        snapshot_ttl_s: float = 1.0,
        error_backoff_s: float = 5.0,
        prefix_prewarm_s: float = 0.25,
    ) -> None:
        self.cluster = cluster
        self.binder = binder
        self.client = client
        self.scheduler_name = scheduler_name
        self.error_backoff_s = error_backoff_s
        self.snapshot_ttl_s = snapshot_ttl_s
        # Advisory prefix prewarming (0 disables): while idle, keep the
        # engine's (prefix KV, grammar) group pointed at the CURRENT
        # cluster snapshot so the first wave of the next burst skips the
        # chunked prefix prefill — the dominant term in the burst1000
        # floor (SCALING.md). `_prewarm_last` is written from the engine
        # worker thread's future callback (str compare/assign only).
        self.prefix_prewarm_s = prefix_prewarm_s
        self._prewarm_last: str | None = None
        self._sem = asyncio.Semaphore(max_concurrency)
        # Blocking (executor) binds get their own bound so they can't
        # monopolize the shared to_thread pool (snapshot runs there too).
        self._bind_sem = asyncio.Semaphore(min(32, max_concurrency))
        self._snapshot: tuple[float, Sequence[NodeMetrics]] | None = None
        self._snapshot_lock = asyncio.Lock()
        self._tasks: set[asyncio.Task] = set()
        # follower fan-out batches parked on in-flight leader futures
        self._followers: dict[asyncio.Future, list] = {}
        # pods currently in the pipeline, keyed (namespace, name): the
        # same pod can reach the scheduler twice concurrently — a watch
        # event racing a fleet rebind re-list (fleet/frontend._rebind),
        # or a kube relist re-delivering a still-in-flight pod. The
        # second copy is suppressed, not double-decided (the loser would
        # waste a model call and fail its bind at the apiserver). All
        # mutations happen on the event loop; completed pods leave the
        # set, so a genuinely re-pending pod (failed bind) retries.
        self._inflight_pods: set[tuple[str, str]] = set()
        self._stop_event = asyncio.Event()
        self.running = False
        # Per-phase wall time of the decision pipeline (SURVEY §5 tracing:
        # the reference has none) — surfaces via get_stats and /metrics.
        self.phases = PhaseRecorder()
        # Optional shadow scorer (rollout/shadow.ShadowScorer): mirrors a
        # fraction of decided pods through a candidate backend, non-binding
        # and off the hot path. Attached by the rollout wiring.
        self.shadow = None
        # Optional shard attribution (fleet/frontend.py): maps a pod's
        # (namespace, name) to its watch-space shard id; when set, every
        # decision trace carries shard_id in its meta so /debug/decisions
        # and `cli trace` answer "which replica's shard was this?".
        self.shard_fn = None
        # Optional in-loop latency probe (engine.resident_decision_latency,
        # attached by the cli run wiring when the backend serves from the
        # persistent loop): ring-served decisions have NO dispatch-fenced
        # engine spans — the work happened inside one resident XLA program
        # — so LLM decisions attach the probe's EWMA as a SYNTHETIC
        # `loop_resident` span and `cli trace show` explains them again.
        self.resident_latency_fn = None
        self.stats = {
            "total_scheduled": 0,
            "llm_decisions": 0,
            "cache_decisions": 0,
            "fallback_decisions": 0,
            "failed_bindings": 0,
            "unschedulable": 0,
        }

    def invalidate_snapshot(self) -> None:
        """Drop the cached node snapshot so the next decision re-reads the
        cluster. Wave-barrier drivers (sim/arena.py) call this between
        waves: each wave must decide against the settled post-bind state
        even when snapshot_ttl_s is set long enough to pin one snapshot
        per wave. Plain assignment — the reader re-checks under its lock."""
        self._snapshot = None

    async def _node_snapshot(self) -> Sequence[NodeMetrics]:
        """Cluster snapshot, reused within snapshot_ttl_s across a burst."""
        async with self._snapshot_lock:
            now = time.monotonic()
            if self._snapshot is not None and now - self._snapshot[0] < self.snapshot_ttl_s:
                return self._snapshot[1]
            metrics = await asyncio.to_thread(self.cluster.get_node_metrics)
            self._snapshot = (time.monotonic(), metrics)
            return metrics

    async def schedule_pod(self, raw: RawPod, pod=None) -> bool:
        """One pod through the full pipeline (reference scheduler.py:690-729).
        Returns True iff the pod was bound. `pod` is the optional
        already-converted PodSpec (the fast path computes it before falling
        through; don't pay raw_pod_to_spec twice on the ingest hot path).

        Each pod gets its own flight-recorder trace (observability/spans):
        snapshot/decide/bind child spans here, backend/admission/prefill/
        decode spans attached downstream (sched/client, engine/local), so
        "why was THIS placement slow?" is answerable from /debug/trace."""
        key = (raw.namespace, raw.name)
        if key in self._inflight_pods:
            logger.debug(
                "duplicate schedule suppressed: %s/%s (already in flight)",
                raw.namespace, raw.name,
            )
            return False
        self._inflight_pods.add(key)
        try:
            if pod is None:
                pod = raw_pod_to_spec(raw)
            with spans.start_trace(
                "decision", pod=f"{pod.namespace}/{pod.name}", path="full"
            ) as trace:
                self._stamp_shard(trace, pod)
                return await self._schedule_pod_inner(pod, trace)
        finally:
            self._inflight_pods.discard(key)

    def _stamp_shard(self, trace, pod) -> None:
        """Shard attribution on the decision trace (all three paths —
        full, fast, follower — call this right after the trace opens)."""
        if trace is not None and self.shard_fn is not None:
            trace.set_meta(shard_id=self.shard_fn(pod.namespace, pod.name))

    def _attach_resident_span(self, trace) -> None:
        """Synthetic `loop_resident` span on an LLM decision: the
        counter-derived EWMA of in-loop admission-to-first-emission
        latency (probe wired by cli run). Marked synthetic=True — it is
        an attribution estimate from device counters, not a fenced
        measurement, and the trace viewer labels it as such. Backdated so
        the span sits inside the decide window it explains."""
        if trace is None or self.resident_latency_fn is None:
            return
        try:
            lat_ms = self.resident_latency_fn()
        except Exception:
            logger.exception("resident latency probe failed")
            return
        if not lat_ms:
            return
        trace.add_span(
            "loop_resident",
            start_unix=time.time() - lat_ms / 1000.0,  # graftlint: ok[raw-clock] — wall ANCHOR backdating a retroactive span (duration comes from device counters)
            dur_ms=float(lat_ms),
            synthetic=True,
        )

    async def _schedule_pod_inner(self, pod, trace) -> bool:
        with self.phases.phase("snapshot"), spans.span("snapshot"):
            nodes = await self._node_snapshot()
        if not nodes:
            logger.warning("no nodes in cluster, leaving %s pending", pod.name)
            self.stats["unschedulable"] += 1
            _stamp_outcome(trace, "unschedulable")
            return False

        with self.phases.phase("decide"), spans.span("decide"):
            # The semaphore is passed THROUGH: the client acquires it only
            # around real backend work. Cache hits and single-flight
            # follower waits never hold a slot (during a burst, followers
            # parked on slots throttled the watch drain behind the wave
            # round trip — measured ~2x p50 inflation), while a follower
            # retrying after a failed leader is still bounded.
            decision = await self.client.get_scheduling_decision(
                pod, nodes, concurrency=self._sem
            )
        if decision is None:
            self.stats["unschedulable"] += 1
            _stamp_outcome(trace, "unschedulable")
            return False

        if decision.source is DecisionSource.FALLBACK:
            self.stats["fallback_decisions"] += 1
        elif decision.source is DecisionSource.CACHE:
            self.stats["cache_decisions"] += 1
        else:
            self.stats["llm_decisions"] += 1
            self._attach_resident_span(trace)
        _stamp_decision(trace, decision)

        if self.shadow is not None:
            # Non-binding candidate mirror (rollout/shadow.py): one counter
            # check + one executor submit; never on the bind critical path,
            # and a broken shadow must never affect real scheduling.
            try:
                self.shadow.observe(pod, nodes, decision)
            except Exception:
                logger.exception("shadow mirror failed")

        if getattr(self.binder, "bind_is_nonblocking", False):
            # In-memory binders (FakeCluster) finish in microseconds; the
            # executor round trip would cost more than the bind and its
            # queue serializes a 1000-pod drain.
            ok = self._bind_now(pod, decision)
        else:
            # Blocking binders go through the shared to_thread executor;
            # bound separately from the decide semaphore so an unbounded
            # flood of cache-hit binds can't saturate the executor and
            # starve _node_snapshot's to_thread behind it.
            async with self._bind_sem:
                with self.phases.phase("bind"), spans.span("bind"):
                    ok = await asyncio.to_thread(
                        self.binder.bind_pod_to_node,
                        pod.name, pod.namespace, decision.selected_node,
                    )
            self._note_bind(ok, pod, decision)
        _stamp_outcome(trace, "bound" if ok else "bind_failed")
        if not ok:
            return False
        logger.info(
            "scheduled %s/%s -> %s (%s, conf=%.2f, %.1fms)",
            pod.namespace,
            pod.name,
            decision.selected_node,
            decision.source.value,
            decision.confidence,
            decision.latency_ms,
        )
        return True

    async def _spawn(self, raw: RawPod, pod=None) -> None:
        # No semaphore here: the client bounds only its backend work, so
        # cache/coalesced decisions drain at host speed during a burst.
        try:
            await self.schedule_pod(raw, pod)
        except Exception:
            logger.exception("unhandled error scheduling %s/%s", raw.namespace, raw.name)

    # ------------------------------------------------------- burst fast path
    def _try_fast(self, raw: RawPod) -> tuple[bool, "PodSpec | None"]:
        """Handle a watch event synchronously on the hot loop when no
        backend work is needed. Returns (handled, pod_spec); an unhandled
        pod's spec is passed to the full path so it isn't converted twice.

        During a 1000-pod burst only ~#shapes decisions need the model;
        everything else is a cache hit or a follower of an in-flight
        single-flight leader. Spawning a task per such pod (round 2) made
        the median pod's latency drain-bound: hundreds of live coroutines
        contended with the engine's wave round trip. Here cache hits bind
        inline and followers park on the leader's future in a LIST — one
        callback flushes the whole batch when the leader resolves, so the
        loop stays idle while the wave is in flight (the pod's latency is
        then one wave round trip, not host scheduling).
        """
        if (raw.namespace, raw.name) in self._inflight_pods:
            return True, None  # duplicate of an in-flight pod: drop it
        if not getattr(self.binder, "bind_is_nonblocking", False):
            return False, None  # blocking binders need the executor path
        snap = self._snapshot
        if snap is None or time.monotonic() - snap[0] >= self.snapshot_ttl_s:
            return False, None  # no fresh snapshot: full path refreshes it
        nodes = snap[1]
        if not nodes:
            return False, None
        pod = raw_pod_to_spec(raw)
        t0 = time.perf_counter()
        t0_wall = time.time()  # graftlint: ok[raw-clock] — wall ANCHOR for span stitching, never a judgment (durations stay perf_counter)
        decision, fut = self.client.fast_decision(pod, nodes)
        if decision is not None:
            # Record the decide phase only when the fast path handles the
            # pod — an unhandled probe falls through to schedule_pod, which
            # records its own decide (double counting otherwise).
            decide_s = time.perf_counter() - t0
            self.phases.record("decide", decide_s)
            self.stats["cache_decisions"] += 1
            # backdated to the watch event: the trace opens after the
            # cache hit resolved, but its root must cover decide + bind
            with spans.start_trace(
                "decision", pod=f"{pod.namespace}/{pod.name}", path="fast",
                start_unix=t0_wall, start_perf=t0,
            ) as trace:
                if trace is not None:
                    trace.add_span(
                        "decide", start_unix=t0_wall,
                        dur_ms=decide_s * 1000.0, cache_hit=True,
                    )
                    # the cache recorded which tier answered (thread-local
                    # on this loop thread, set by the fast_decision lookup
                    # just above): l1_hit, or l2_hit via a fleet-shared L2
                    tier = getattr(self.client.cache, "last_tier", None)
                    if tier is not None:
                        trace.set_meta(cache_tier=tier)
                self._stamp_shard(trace, pod)
                _stamp_decision(trace, decision)
                try:
                    ok = self._bind_now(pod, decision)
                    _stamp_outcome(trace, "bound" if ok else "bind_failed")
                except Exception:
                    # Contained HERE, pod counts as handled: re-running it
                    # through the full path would double-count the decide/
                    # cache stats just recorded (and could double-bind). A
                    # raising binder is accounted like a failed bind; the
                    # pod stays Pending and the watch re-observes it.
                    self.stats["failed_bindings"] += 1
                    _stamp_outcome(trace, "bind_raised")
                    logger.exception(
                        "fast-path bind raised: %s/%s", pod.namespace, pod.name
                    )
            return True, pod
        if fut is not None:
            batch = self._followers.get(fut)
            if batch is None:
                self._followers[fut] = batch = []
                fut.add_done_callback(self._flush_followers)
            # parked followers are in flight until the flush binds them
            self._inflight_pods.add((raw.namespace, raw.name))
            batch.append((raw, pod, t0, t0_wall))
            return True, pod
        return False, pod

    def _bind_now(self, pod, decision) -> bool:
        """Synchronous bind + bookkeeping (nonblocking binders only)."""
        with self.phases.phase("bind"), spans.span("bind"):
            ok = self.binder.bind_pod_to_node(
                pod.name, pod.namespace, decision.selected_node
            )
        self._note_bind(ok, pod, decision)
        return ok

    def _note_bind(self, ok: bool, pod, decision) -> None:
        """The ONE place bind outcomes are accounted (fast path, full path,
        follower flush all converge here)."""
        if ok:
            self.stats["total_scheduled"] += 1
        else:
            self.stats["failed_bindings"] += 1
            logger.error(
                "binding failed: %s/%s -> %s",
                pod.namespace, pod.name, decision.selected_node,
            )

    def _flush_followers(self, fut: asyncio.Future) -> None:
        """Leader resolved: bind its parked followers in one pass, or (on a
        failed/fallback leader) degrade each to the full path."""
        batch = self._followers.pop(fut, [])
        if not batch:
            return
        leader = None
        if not fut.cancelled():
            leader = fut.result()  # single-flight futures never hold exceptions
        if leader is not None:
            self.client.note_coalesced(len(batch))
            decision = dataclasses.replace(leader, source=DecisionSource.CACHE)
            now = time.perf_counter()
            for _raw, pod, parked_at, parked_wall in batch:
                # Per-item isolation: one raising bind must not drop the
                # rest of the batch (this runs in a future done-callback).
                try:
                    # follower decide duration = park -> leader resolution,
                    # matching what the shield-await path used to measure
                    self.phases.record("decide", now - parked_at)
                    self.stats["cache_decisions"] += 1
                    # backdated to the park time: the root covers the
                    # whole park -> leader -> bind interval, not just bind
                    with spans.start_trace(
                        "decision", pod=f"{pod.namespace}/{pod.name}",
                        path="follower",
                        start_unix=parked_wall, start_perf=parked_at,
                    ) as trace:
                        if trace is not None:
                            trace.add_span(
                                "decide", start_unix=parked_wall,
                                dur_ms=(now - parked_at) * 1000.0,
                                coalesced=True,
                            )
                            # a follower never consulted the cache: its
                            # decision is the leader's, reused in flight
                            trace.set_meta(cache_tier="coalesced")
                        self._stamp_shard(trace, pod)
                        _stamp_decision(trace, decision)
                        ok = self._bind_now(pod, decision)
                        _stamp_outcome(trace, "bound" if ok else "bind_failed")
                except Exception:
                    self.stats["failed_bindings"] += 1
                    logger.exception(
                        "follower bind failed: %s/%s", pod.namespace, pod.name
                    )
                finally:
                    self._inflight_pods.discard((_raw.namespace, _raw.name))
        else:
            # leader failed or fell back: each follower decides on the full
            # path (which records its own decide phase). Release the park
            # key first — schedule_pod re-adds it (and would otherwise
            # suppress its own retry as a duplicate).
            for raw, pod, _t0, _t0w in batch:
                self._inflight_pods.discard((raw.namespace, raw.name))
                task = asyncio.create_task(self._spawn(raw, pod))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)

    async def _prefix_prewarm_loop(self) -> None:
        """Keep the engine's prefix group pointed at the current cluster
        snapshot while idle (engine/local.prewarm_prefix — advisory: the
        engine drops installs whenever real traffic is in flight). The
        rendered cluster prefix is the change signature: re-prewarm only
        when the snapshot's PROMPT TEXT changed, so a steady-state tick
        costs one ~0.1 ms render plus at most 1/snapshot_ttl_s snapshot
        refreshes — and a refresh is an in-memory read for this repo's
        ClusterState impls (cluster/kube.py is a watch-driven informer
        serving get_node_metrics from its local cache with zero API
        calls; cluster/fake.py is memory), NOT recurring apiserver load.
        A polling ClusterState impl would pay its poll here at 1 Hz; gate
        with scheduler.prefix_prewarm_seconds: 0 in that case. Exits on
        the first tick if the backend doesn't support prewarming."""
        from k8s_llm_scheduler_tpu.core.prompt import PromptEngine

        pe = PromptEngine()
        while not self._stop_event.is_set():
            try:
                await asyncio.wait_for(
                    self._stop_event.wait(), timeout=self.prefix_prewarm_s
                )
                return
            except asyncio.TimeoutError:
                pass
            if self._tasks:
                # Decisions in flight: the engine would drop the install
                # anyway (real traffic decides groups) — skip the render/
                # encode entirely instead of blocking the event loop at
                # tick rate for the whole burst. The tick resumes once the
                # burst drains, when the snapshot has settled post-binds.
                continue
            try:
                nodes = await self._node_snapshot()
                sig = pe.cluster_part(nodes)
                if sig == self._prewarm_last:
                    continue
                # to_thread: the local backend's prewarm_prefix is a queue
                # put, but a FanoutBackend forwards over the decision-RPC
                # wire — ReplicaClient may BLOCK dialing a dead worker for
                # connect_timeout_s, which must not wedge the event loop
                fut = await asyncio.to_thread(
                    self.client.prewarm_prefix, nodes
                )
                if fut is None:
                    return  # backend can't prewarm; stop ticking
                self._prewarm_last = sig

                def _done(f, s=sig):
                    # engine-worker thread: GIL-atomic compare/assign only.
                    # A dropped install (engine busy) clears the signature
                    # so the next tick retries.
                    try:
                        ok = f.result()
                    except Exception:
                        ok = False
                    if not ok and self._prewarm_last == s:
                        self._prewarm_last = None

                fut.add_done_callback(_done)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prefix prewarm tick failed")

    async def run(self) -> None:
        """Watch loop: stream pending pods, schedule each concurrently.
        Self-heals on stream errors (reference scheduler.py:683-685).
        stop() terminates the loop even while the watch stream is idle —
        each stream read is raced against the stop event."""
        if self._stop_event.is_set():
            return  # stop() was called before run() got scheduled
        self.running = True
        # ONE long-lived stop-wait task raced against every stream read: a
        # fresh task per pod costs two task creations + a cancel on the
        # ingest hot path (~50 ms across a 1000-pod burst).
        stop_task = asyncio.ensure_future(self._stop_event.wait())
        prewarm_task = (
            asyncio.create_task(self._prefix_prewarm_loop())
            if self.prefix_prewarm_s > 0
            else None
        )
        try:
            while self.running:
                stream = None
                try:
                    stream = self.cluster.watch_pending_pods(self.scheduler_name).__aiter__()
                    while self.running:
                        next_task = asyncio.ensure_future(anext(stream))
                        done, _ = await asyncio.wait(
                            {next_task, stop_task}, return_when=asyncio.FIRST_COMPLETED
                        )
                        if stop_task in done and next_task not in done:
                            next_task.cancel()
                            try:
                                await next_task  # let the generator settle
                            except (asyncio.CancelledError, StopAsyncIteration):
                                pass
                            break
                        try:
                            raw = next_task.result()
                        except StopAsyncIteration:
                            break
                        pod = None
                        try:
                            handled, pod = self._try_fast(raw)
                        except Exception:
                            # Per-pod containment: a poison event must not
                            # tear down the watch stream (the full path has
                            # its own try/except in _spawn).
                            handled = False
                            logger.exception(
                                "fast path failed for %s/%s",
                                raw.namespace, raw.name,
                            )
                        if handled:
                            continue
                        task = asyncio.create_task(self._spawn(raw, pod))
                        self._tasks.add(task)
                        task.add_done_callback(self._tasks.discard)
                    break  # stream ended cleanly or stop requested
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception(
                        "watch stream error, re-watching in %.1fs", self.error_backoff_s
                    )
                    await asyncio.sleep(self.error_backoff_s)
                finally:
                    if stream is not None and hasattr(stream, "aclose"):
                        # Run the generator's cleanup (stops kube watch threads).
                        await stream.aclose()
        finally:
            stop_task.cancel()
            try:
                await stop_task
            except asyncio.CancelledError:
                pass
            if prewarm_task is not None:
                prewarm_task.cancel()
                try:
                    await prewarm_task
                except asyncio.CancelledError:
                    pass
        await self.drain()

    async def drain(self) -> None:
        """Wait for all in-flight scheduling tasks."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    def stop(self) -> None:
        """Request loop termination; safe to call before or during run()."""
        self.running = False
        self._stop_event.set()

    def get_stats(self) -> dict:
        out = {
            **self.stats,
            "client": self.client.get_stats(),
            "phases": self.phases.snapshot(),
        }
        if self.shadow is not None:
            out["shadow"] = self.shadow.stats()
        return out
