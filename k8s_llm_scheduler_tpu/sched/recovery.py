"""Crash-restart recovery: rebuild a replica's bind obligations from disk.

The journal (sched/journal.py) records the decision -> bind-intent ->
bind-ack lifecycle; this module is the other half — the protocol a
restarting replica runs BEFORE it takes traffic:

1. replay the journal (done by DecisionJournal at open: torn tail
   truncated, state folded);
2. restore the circuit breaker from its journaled snapshot, so a
   rebooted replica does not hammer a backend the fleet already knows
   is down (OPEN resumes with its remaining jittered cooldown);
3. reconcile every OPEN lifecycle against the cluster's actual
   ``pod.spec.nodeName`` — the cluster, not the journal, is the
   authority on what landed:

   ========== ==========================================================
   cluster    action
   ========== ==========================================================
   bound      the bind landed before the crash (or someone else's did):
              journal the missing ack, nothing to re-execute
   pending    the decision survived but the bind did not: complete the
              bind through the caller's binder chain — under a
              re-acquired fenced lease in a fleet — WITHOUT re-deciding
              (the journaled node IS the decision)
   gone       the pod was deleted while we were down: journal a drop
   ========== ==========================================================

4. resume the watch from the journaled resourceVersion (the caller
   passes ``state.last_rv`` to its cluster driver — cluster/kube.py
   ``resume_rv``), paying one reconciling relist instead of a blind
   fresh start.

Recovery writes only journal APPENDS (acks/drops/fresh intents), so it
is itself crash-consistent: a crash mid-recovery leaves a journal whose
next replay reconciles the remainder — the chaos plane's
crash-during-recovery regime pins exactly that.

`JournaledBinder` is the production seam that feeds the journal: every
bind path (full, fast, follower, rebind, recovery) converges on the
Binder, so wrapping it records the whole lifecycle with one wrapper.
The chaos `process` seam rides the same wrapper (``crash_seam``, None
in production) to drop a replica cold at the nastiest points.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable

from k8s_llm_scheduler_tpu.sched.journal import DecisionJournal

logger = logging.getLogger(__name__)

# JournaledBinder kill points, in lifecycle order (chaos process seam):
# post_decide = decision journaled, intent not; mid_bind = intent
# journaled, bind NOT executed; post_bind = bind executed, ack not.
CRASH_POINTS = ("post_decide", "mid_bind", "post_bind")


class SimulatedCrash(RuntimeError):
    """Chaos-injected cold process death (never raised in production:
    it fires only through a non-None crash_seam). The harness catches
    it, discards the replica object with leases UNRELEASED, and rebuilds
    from disk."""

    def __init__(self, point: str, subject: str) -> None:
        super().__init__(f"simulated crash at {point} ({subject})")
        self.point = point
        self.subject = subject


class JournaledBinder:
    """Binder wrapper recording the decide/intent/ack lifecycle.

    Sits INSIDE the lease fence (fleet/frontend.py wraps it in
    _FencedBinder): a fenced-off bind never journals, so recovery never
    chases obligations this replica was not allowed to create. The
    decide record is written here too — the binder receives the chosen
    node, and every scheduler path (full, fast, follower, rebind)
    converges on it, so one wrapper covers the whole lifecycle without
    touching three hot paths."""

    def __init__(
        self,
        inner: Any,
        journal: DecisionJournal,
        *,
        shard_fn: Callable[[str, str], int] | None = None,
        epoch_fn: Callable[[int], int | None] | None = None,
    ) -> None:
        self._inner = inner
        self._journal = journal
        self._shard_fn = shard_fn
        self._epoch_fn = epoch_fn
        # Chaos seam (chaos/faults.py seam "process"): None in production
        # — one attribute read per bind.
        self.crash_seam = None
        self.crashed: tuple[str, str] | None = None  # (point, subject)
        # preserve the scheduler's inline-bind fast path
        self.bind_is_nonblocking = getattr(inner, "bind_is_nonblocking", False)

    def _crash(self, point: str, subject: str) -> None:
        seam = self.crash_seam
        if seam is None:
            return
        event = seam.should("crash", key=subject, where={"point": point})
        if event is not None:
            self.crashed = (point, subject)
            raise SimulatedCrash(point, subject)

    def bind_pod_to_node(
        self, pod_name: str, namespace: str, node_name: str
    ) -> bool:
        subject = f"{namespace}/{pod_name}"
        shard = (
            self._shard_fn(namespace, pod_name)
            if self._shard_fn is not None else None
        )
        epoch = (
            self._epoch_fn(shard)
            if self._epoch_fn is not None and shard is not None else None
        )
        self._journal.record_decide(namespace, pod_name, node_name)
        self._crash("post_decide", subject)
        self._journal.record_intent(
            namespace, pod_name, node_name, shard=shard, epoch=epoch
        )
        self._crash("mid_bind", subject)
        ok = self._inner.bind_pod_to_node(pod_name, namespace, node_name)
        self._crash("post_bind", subject)
        self._journal.record_ack(namespace, pod_name, node_name, ok)
        return ok

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


@dataclasses.dataclass
class RecoveryReport:
    """What one recovery pass reconciled."""

    acked: int = 0      # bind had landed: missing ack journaled
    rebound: int = 0    # bind had not landed: completed without re-deciding
    dropped: int = 0    # pod gone: lifecycle closed
    failed: int = 0     # completion bind refused (fence/cluster said no)
    breaker_restored: bool = False
    resume_rv: str | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def reconciled(self) -> int:
        return self.acked + self.rebound + self.dropped


# pod_lookup contract: (namespace, name) -> ("bound", node) |
# ("pending", None) | ("gone", None). cluster/kube.py lookup_pod_node
# and cluster/fake.py get_pod both back it trivially.
PodLookup = Callable[[str, str], tuple[str, "str | None"]]


def recover(
    journal: DecisionJournal,
    *,
    pod_lookup: PodLookup,
    binder: Any,
    breaker: Any = None,
    crash_seam: Any = None,
) -> RecoveryReport:
    """Run the recovery protocol (module docstring) over an OPEN journal.

    `binder` must be the replica's full bind chain (fence + journal +
    monitors), so completions are fenced and re-journaled exactly like
    live binds. Deterministic: lifecycles reconcile in sorted order.
    `crash_seam` is the chaos process seam (None in production) — the
    crash-during-recovery regime consumes one `crash_recovery` event
    after a reconcile action lands, proving recovery is re-entrant."""
    report = RecoveryReport()
    state = journal.state
    report.resume_rv = state.last_rv
    if breaker is not None and state.breaker is not None:
        try:
            breaker.restore(state.breaker)
            report.breaker_restored = True
        except Exception:
            logger.exception("breaker restore failed; starting CLOSED")
    open_lifecycles = state.open_lifecycles()
    for (ns, name), rec in sorted(open_lifecycles.items()):
        status, node_now = pod_lookup(ns, name)
        if status == "gone":
            journal.record_drop(ns, name, "pod gone at recovery")
            report.dropped += 1
        elif status == "bound":
            # landed before the crash (to our node, or — lease failover
            # while we were down — to someone else's choice); either way
            # the obligation is discharged, record the truth
            journal.record_ack(ns, name, node_now, ok=True, recovered=True)
            report.acked += 1
        else:
            # decided but unbound: complete WITHOUT re-deciding. The
            # chain fences this under the re-acquired lease; a refusal
            # (fence lost, cluster said no) leaves the pod pending for
            # the shard's live owner — never silently dropped.
            ok = binder.bind_pod_to_node(name, ns, rec["node"])
            if ok:
                report.rebound += 1
            else:
                report.failed += 1
                logger.warning(
                    "recovery: completion bind refused for %s/%s -> %s "
                    "(pod stays pending)", ns, name, rec["node"],
                )
        if crash_seam is not None:
            event = crash_seam.should("crash_recovery", key=f"{ns}/{name}")
            if event is not None:
                raise SimulatedCrash("recovery", f"{ns}/{name}")
    logger.info(
        "recovery: %d acked, %d rebound, %d dropped, %d refused "
        "(resume rv=%s, breaker %s)",
        report.acked, report.rebound, report.dropped, report.failed,
        report.resume_rv,
        "restored" if report.breaker_restored else "fresh",
    )
    return report
