"""Cross-host decision serving: replica workers + coordinator fan-out.

SCALING.md's multi-host serving layout is replica-per-host (weights
replicated over hosts, tp within each host's ICI domain) — but through
round 3 only the coordinator actually SERVED: workers had a backend and no
way to receive work. This module is the missing transport:

- `ReplicaServer`: runs on a worker host next to its LocalLLMBackend;
  accepts length-delimited JSON requests over TCP and answers each with
  the backend's SchedulingDecision. Connections are handled on threads and
  requests WITHIN a connection are executed concurrently — the worker's
  engine sees the same concurrency a local DecisionClient would produce,
  so its wave batching still coalesces a burst's leaders.
- `ReplicaClient`: a MULTIPLEXING client (one socket, id-tagged frames, a
  reader thread resolving per-request futures). Concurrent coordinator
  requests interleave on the wire instead of serializing, which is what
  keeps the remote engine's waves full.
- `FanoutBackend`: the coordinator-side DecisionBackend that round-robins
  decisions across [local backend, replica clients...]. It sits BELOW
  DecisionClient, so the cache / single-flight / breaker / fallback stack
  is untouched: only leader decisions (cache misses) ever reach a replica.

The control plane stays coordinator-only (watch/bind; parallel/
distributed.is_coordinator) — what fans out is pure model compute, the
part that scales with replica count. K8s traffic does not multiply.

Transport is dependency-free (socket + json + threading): 4-byte
big-endian length prefix, UTF-8 JSON payload. Request:
{"id": n, "pod": {...}, "nodes": [...]}; response: {"id": n,
"decision": {...}} | {"id": n, "error": str, "kind":
"infeasible"|"backend"}.

Validated end to end (two real processes, decisions on both) by
tools/dryrun_multihost.py; protocol/fan-out unit tests in
tests/test_replica.py.
"""

from __future__ import annotations

import dataclasses
import inspect
import itertools
import json
import logging
import math
import random
import socket
import struct
import threading
import time
from collections import deque
from collections.abc import Sequence
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Callable

from k8s_llm_scheduler_tpu.engine.backend import (
    BackendError,
    DecisionBackend,
    NoFeasibleNodeError,
)
from k8s_llm_scheduler_tpu.observability import spans
from k8s_llm_scheduler_tpu.sched import deadline as deadline_mod
from k8s_llm_scheduler_tpu.sched.deadline import (
    DeadlineBudget,
    DeadlineExceededError,
)
from k8s_llm_scheduler_tpu.types import (
    DecisionSource,
    NodeMetrics,
    PodSpec,
    SchedulingDecision,
)

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20  # sanity bound; a 10k-pod snapshot is ~3 MB of JSON


# ------------------------------------------------------------------ frames
def _encode_frame(obj: dict) -> tuple[bytes, bytes]:
    """(length header, JSON payload) — encoded once; the payload bytes are
    handed to the kernel as a memoryview and never copied again."""
    payload = json.dumps(obj).encode("utf-8")
    return _LEN.pack(len(payload)), payload


def _send_frames(
    sock: socket.socket, frames: "Sequence[tuple[bytes, bytes]]"
) -> None:
    """Zero-copy vectored frame write: every frame's (header, payload)
    pair joins ONE scatter-gather `sendmsg` iovec — no header+payload
    concatenation (the old path copied every payload a second time), and
    a BATCH of frames costs one syscall instead of one per frame (the
    client's outbox coalescing rides on exactly this). Partial sends
    advance through the iovec with memoryview slices; sockets without
    sendmsg fall back to per-buffer sendall."""
    bufs: list[memoryview] = []
    for header, payload in frames:
        bufs.append(memoryview(header))
        bufs.append(memoryview(payload))
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:  # pragma: no cover - platform without sendmsg
        for b in bufs:
            sock.sendall(b)
        return
    # The kernel caps one sendmsg at IOV_MAX iovecs (1024 on Linux): a
    # large drained outbox batch must chunk or a HEALTHY socket raises
    # EMSGSIZE and the flush wrongly fails every batchmate.
    iov_max = min(getattr(socket, "IOV_MAX", 1024), 1024)
    while bufs:
        n = sendmsg(bufs[:iov_max])
        while n:
            if n >= len(bufs[0]):
                n -= len(bufs[0])
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][n:]
                n = 0


def _send_frame(sock: socket.socket, obj: dict) -> None:
    _send_frames(sock, [_encode_frame(obj)])


def _set_nodelay(sock: socket.socket) -> None:
    """Disable Nagle: decision frames are small and latency-critical —
    leaving coalescing to the kernel adds up to one delayed-ACK round
    trip (~40ms) per frame, a direct dispatch_rtt_ms term. Batching is
    done deliberately at the framing layer (_send_frames) instead."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # best-effort (some socketpairs/platforms refuse)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise BackendError(f"replica frame of {length} bytes exceeds bound")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return json.loads(payload.decode("utf-8"))


# ------------------------------------------------------------- serialization
def pod_to_wire(pod: PodSpec) -> dict:
    return dataclasses.asdict(pod)


def pod_from_wire(d: dict) -> PodSpec:
    d = dict(d)
    d["tolerations"] = tuple(d.get("tolerations") or ())
    return PodSpec(**d)


def node_to_wire(node: NodeMetrics) -> dict:
    return dataclasses.asdict(node)


def node_from_wire(d: dict) -> NodeMetrics:
    d = dict(d)
    d["taints"] = tuple(d.get("taints") or ())
    return NodeMetrics(**d)


def decision_to_wire(dec: SchedulingDecision) -> dict:
    d = dataclasses.asdict(dec)
    d["source"] = dec.source.value
    return d


def decision_from_wire(d: dict) -> SchedulingDecision:
    d = dict(d)
    d["source"] = DecisionSource(d["source"])
    return SchedulingDecision(**d)


# ------------------------------------------------------------------- server
class ReplicaServer:
    """Serve a DecisionBackend over TCP on a worker host.

    One accept thread; one reader thread per connection; requests within a
    connection run CONCURRENTLY on a bounded executor (`max_inflight`) —
    the engine's wave batching depends on seeing the burst's leaders
    together, and the engine-owner thread in LocalLLMBackend already
    serializes device access safely, but an unbounded thread-per-request
    design let any client spawn unbounded threads.

    Trust model: the protocol is unauthenticated JSON-RPC that drives model
    compute — it must only be reachable from the coordinator. The default
    bind is loopback; multi-host deployments set
    `distributed.replica_bind_host` to the worker's pod/host IP (or
    explicitly to "0.0.0.0" on a trusted network).
    """

    def __init__(self, backend: DecisionBackend, host: str = "localhost",
                 port: int = 9901, max_inflight: int = 64,
                 max_connections: int = 16,
                 swap_fn: Callable[[int], dict] | None = None,
                 pool_role: str = "mixed",
                 telemetry_fn: Callable[[dict], dict] | None = None) -> None:
        from concurrent.futures import ThreadPoolExecutor

        from k8s_llm_scheduler_tpu.fleet.pools import POOL_ROLES

        self.backend = backend
        # Disaggregated-pool role (fleet/pools.py): a "decode" worker
        # refuses admission (work="prefill") frames so a misrouting fleet
        # frontend fails loudly instead of silently evicting decode
        # throughput. "mixed" (default) accepts everything — single-pool
        # deployments are unchanged.
        if pool_role not in POOL_ROLES:
            raise ValueError(
                f"pool_role {pool_role!r} not in {POOL_ROLES}"
            )
        self.pool_role = pool_role
        # capability probes, ONCE (not per request): does the backend
        # understand the work tag / the prepacked batch surface?
        try:
            self._backend_accepts_work = "work" in inspect.signature(
                backend.get_scheduling_decision
            ).parameters
        except (TypeError, ValueError):
            self._backend_accepts_work = False
        self._backend_batch = getattr(
            backend, "get_scheduling_decisions_batch", None
        )
        # Optional rollout hook: `swap_fn(version) -> dict` hot-swaps THIS
        # worker's backend to a registry version (rollout/hotswap.py
        # HotSwapper.swap_to over a registry the worker can read). The
        # coordinator's canary controller staggers these one replica at a
        # time (rollout/canary.staggered_swap) so the fanout always keeps
        # a serving majority. None = the op answers ok=False.
        self.swap_fn = swap_fn
        # Fleet telemetry hook (observability/fleetview.py): the
        # `telemetry_pull` op ships this worker's stats tree, a
        # since-cursor flight-recorder slice, and its sampler ring to the
        # aggregator. `telemetry_fn(request) -> payload` overrides the
        # default (backend stats + the process-global flight recorder) for
        # deployments that wire a scheduler-level stats provider.
        self.telemetry_fn = telemetry_fn
        self.max_inflight = max_inflight
        self.max_connections = max_connections
        self._pool = ThreadPoolExecutor(
            max_workers=max_inflight, thread_name_prefix="replica-req"
        )
        # in-flight = queued + executing: the executor's own queue is
        # unbounded, so admission is gated here — excess requests get an
        # immediate "overloaded" error response instead of queueing
        # unbounded memory
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]  # resolved (port=0 allowed)
        self._stop = threading.Event()
        self.served = 0
        self._served_lock = threading.Lock()
        # live per-connection sockets: close() must shut these down too —
        # closing only the listener left connection threads serving
        # requests after "shutdown" (a stopped worker kept answering)
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="replica-accept"
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            logger.info("replica: accepted connection from %s:%s", *addr[:2])
            threading.Thread(
                target=self._serve_conn, args=(conn, addr), daemon=True,
                name=f"replica-conn-{addr[1]}",
            ).start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        _set_nodelay(conn)
        send_lock = threading.Lock()
        with self._conns_lock:
            if self._stop.is_set() or len(self._conns) >= self.max_connections:
                # connection cap: each live connection holds a reader
                # thread; without this bound any reachable peer could
                # spawn unbounded threads by dialing in a loop
                conn.close()
                return
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                req = _recv_frame(conn)
                if req is None:
                    return
                cost = self._frame_cost(req)
                with self._inflight_lock:
                    admitted = self._inflight < self.max_inflight
                    if admitted:
                        self._inflight += cost
                if not admitted:
                    # fail fast instead of queueing unbounded: the
                    # coordinator's retry/fallback stack absorbs this
                    # exactly like any other backend error
                    try:
                        with send_lock:
                            _send_frame(conn, {
                                "id": req.get("id"),
                                "error": f"replica overloaded "
                                         f"(>{self.max_inflight} in flight)",
                                "kind": "backend",
                            })
                    except OSError:
                        return
                    continue
                try:
                    self._pool.submit(self._serve_one, conn, send_lock, req)
                except RuntimeError:
                    with self._inflight_lock:
                        self._inflight -= cost
                    return  # pool shut down by close()
        except Exception as exc:
            # broad on purpose: _recv_frame's frame-size guard raises
            # BackendError, and ANY reader failure must take the logged
            # drop path, not kill the thread via excepthook
            if not self._stop.is_set():
                logger.warning("replica connection %s dropped: %s", addr, exc)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _serve_one(self, conn, send_lock, req: dict) -> None:
        rid = req.get("id")
        try:
            if req.get("op") == "rollout_swap":
                # Synchronous on this pool slot ON PURPOSE: the caller
                # staggers replicas one at a time and needs the verdict
                # before touching the next one; decision traffic on other
                # slots keeps flowing until the backend's own quiesce
                # barrier holds it (engine/local.run_quiesced). The
                # enclosing finally/send tail does the inflight decrement
                # and frame send exactly like a decision response.
                if self.swap_fn is None:
                    resp = {"id": rid, "ok": False,
                            "error": "replica has no swap hook"}
                else:
                    try:
                        detail = self.swap_fn(int(req["version"]))
                        resp = {"id": rid, "ok": True, "detail": detail}
                    except Exception as exc:
                        resp = {"id": rid, "ok": False, "error": str(exc)}
            elif req.get("op") == "prewarm":
                # Advisory prefix install forwarded by the coordinator
                # (engine/local.prewarm_prefix semantics). The response is
                # sent from the backend future's callback, so this pool
                # slot frees immediately (the return runs the finally's
                # inflight decrement); a backend without prewarm support
                # answers ok=False.
                self._serve_prewarm(conn, send_lock, req)
                return
            elif req.get("op") == "telemetry_pull":
                # Fleet telemetry fan-in (observability/fleetview.py):
                # stats + since-cursor trace slices + sampler ring, size-
                # capped so 16 replicas can't ship unbounded JSONL.
                resp = {"id": rid, **self._serve_telemetry(req)}
            elif req.get("op") == "decide_batch":
                # Prepacked admission (fleet/pools.py): many pods, ONE
                # nodes snapshot, one frame — per-pod outcomes ride back
                # positionally so one infeasible pod doesn't fail its
                # batchmates.
                resp = self._serve_batch(rid, req)
            else:
                pod = pod_from_wire(req["pod"])
                nodes = [node_from_wire(n) for n in req["nodes"]]
                work = req.get("work", "prefill")
                self._check_role(work)
                # Deadline budget riding the frame (sched/deadline.py):
                # the client stamped its REMAINING ms at send time. An
                # already-expired frame is refused before it can burn a
                # wave on a decision nobody is waiting for; otherwise the
                # budget is re-installed ambiently so a nested backend
                # (local engine behind this server) sees the same clock.
                wire_deadline = req.get("deadline_ms")
                budget = None
                if wire_deadline is not None:
                    if float(wire_deadline) <= 0.0:
                        raise DeadlineExceededError(
                            f"frame arrived with expired deadline "
                            f"({float(wire_deadline):.1f}ms remaining)"
                        )
                    budget = DeadlineBudget.start(float(wire_deadline))
                wire_trace = req.get("trace")
                if wire_trace and spans.enabled():
                    # Continue the COORDINATOR's trace on this side: same
                    # trace id, rooted under the caller's span, so the
                    # stitched tree shows exactly where the wire hop sits.
                    # The worker-side spans ride back in the response for
                    # the client to graft (ReplicaClient._resolve); the
                    # worker's own flight recorder keeps a copy too.
                    with spans.start_trace(
                        "replica.decide",
                        trace_id=str(wire_trace.get("trace_id")),
                        parent_id=str(wire_trace.get("span_id")),
                        pod=f"{pod.namespace}/{pod.name}",
                    ) as rtrace:
                        with deadline_mod.running(budget):
                            decision = self._decide(pod, nodes, work)
                    resp = {
                        "id": rid,
                        "decision": decision_to_wire(decision),
                        "spans": [s.to_dict() for s in rtrace.spans]
                        if rtrace is not None
                        else [],
                    }
                else:
                    with deadline_mod.running(budget):
                        decision = self._decide(pod, nodes, work)
                    resp = {"id": rid, "decision": decision_to_wire(decision)}
            with self._served_lock:
                self.served += 1
        except NoFeasibleNodeError as exc:
            resp = {"id": rid, "error": str(exc), "kind": "infeasible"}
        except DeadlineExceededError as exc:
            resp = {"id": rid, "error": str(exc), "kind": "deadline"}
        except Exception as exc:
            resp = {"id": rid, "error": str(exc), "kind": "backend"}
        finally:
            with self._inflight_lock:
                self._inflight -= self._frame_cost(req)
        try:
            with send_lock:
                _send_frame(conn, resp)
        except OSError:
            pass  # client gone; nothing to deliver to

    @staticmethod
    def _frame_cost(req: dict) -> int:
        """Admission weight of a frame against max_inflight. A
        decide_batch carries up to prepack_max_batch decisions — counting
        it as 1 would let an admission burst admit max_inflight*batch
        concurrent backend decisions, defeating the overload fail-fast
        exactly when prepacking concentrates load. A frame with headroom
        always admits (the predicate checks before adding), so a batch
        larger than max_inflight is still servable, one at a time."""
        if req.get("op") == "decide_batch":
            pods = req.get("pods")
            return max(1, len(pods)) if isinstance(pods, list) else 1
        return 1

    def _check_role(self, work: str) -> None:
        from k8s_llm_scheduler_tpu.fleet.pools import check_pool_role

        check_pool_role(self.pool_role, work)

    def _decide(
        self, pod: PodSpec, nodes: list[NodeMetrics], work: str
    ) -> SchedulingDecision:
        if self._backend_accepts_work:
            return self.backend.get_scheduling_decision(
                pod, nodes, work=work
            )
        return self.backend.get_scheduling_decision(pod, nodes)

    def _serve_telemetry(self, req: dict) -> dict:
        from k8s_llm_scheduler_tpu.observability import fleetview, spans

        if self.telemetry_fn is not None:
            return self.telemetry_fn(req)
        get_stats = getattr(self.backend, "get_stats", None)
        stats = get_stats() if get_stats is not None else {}
        return fleetview.build_telemetry(
            stats,
            spans.flight,
            since_seq=int(req.get("since", 0)),
            max_traces=min(
                int(req.get("max_traces", fleetview.DEFAULT_MAX_TRACES)),
                4 * fleetview.DEFAULT_MAX_TRACES,
            ),
            max_bytes=min(
                int(req.get("max_bytes", fleetview.DEFAULT_MAX_BYTES)),
                4 * fleetview.DEFAULT_MAX_BYTES,
            ),
        )

    def _serve_batch(self, rid, req: dict) -> dict:
        nodes = [node_from_wire(n) for n in req["nodes"]]
        work = req.get("work", "prefill")
        self._check_role(work)
        pods = [pod_from_wire(p) for p in req["pods"]]
        # deadline parity with _serve (the single-decision path): an
        # expired batch frame is refused BEFORE it can burn a prefill
        # wave, and the remaining budget is re-installed ambiently
        wire_deadline = req.get("deadline_ms")
        budget = None
        if wire_deadline is not None:
            if float(wire_deadline) <= 0.0:
                exc = DeadlineExceededError(
                    f"batch frame arrived with expired deadline "
                    f"({float(wire_deadline):.1f}ms remaining)"
                )
                return {"id": rid, "results": [
                    {"error": str(exc), "kind": "deadline"} for _ in pods
                ]}
            budget = DeadlineBudget.start(float(wire_deadline))
        results: list[dict] = []
        with deadline_mod.running(budget):
            if self._backend_batch is not None:
                # the backend's own batch surface (LocalLLMBackend
                # enqueues the whole pack before waiting — the engine
                # admits it as one prefill wave, which is the point of
                # prepacking)
                outcomes = self._backend_batch(pods, nodes, work=work)
            else:
                outcomes = []
                for pod in pods:
                    try:
                        outcomes.append(self._decide(pod, nodes, work))
                    except Exception as exc:
                        outcomes.append(exc)
        for outcome in outcomes:
            if isinstance(outcome, SchedulingDecision):
                results.append({"decision": decision_to_wire(outcome)})
            elif isinstance(outcome, NoFeasibleNodeError):
                results.append({"error": str(outcome), "kind": "infeasible"})
            elif isinstance(outcome, DeadlineExceededError):
                # degrade at the caller, don't retry, don't count a
                # breaker failure (sched/client.py non-failure contract)
                results.append({"error": str(outcome), "kind": "deadline"})
            else:
                results.append({"error": str(outcome), "kind": "backend"})
        return {"id": rid, "results": results}

    def _serve_prewarm(self, conn, send_lock, req: dict) -> None:
        rid = req.get("id")

        def reply(ok: bool) -> None:
            try:
                with send_lock:
                    _send_frame(conn, {"id": rid, "ok": ok})
            except OSError:
                pass  # client gone; nothing to deliver to

        fn = getattr(self.backend, "prewarm_prefix", None)
        if fn is None:
            reply(False)
            return
        try:
            nodes = [node_from_wire(n) for n in req["nodes"]]
            fut = fn(nodes)
        except Exception:
            logger.exception("replica prewarm failed")
            reply(False)
            return

        def _done(f) -> None:
            # Runs on the ENGINE worker thread (the backend resolves its
            # prewarm futures there): writing to a slow client socket here
            # would wedge ALL decision serving behind one blocked send.
            # Hand the reply to the request pool; the engine thread only
            # pays a submit.
            try:
                ok = bool(f.result())
            except Exception:
                ok = False
            try:
                self._pool.submit(reply, ok)
            except RuntimeError:
                pass  # pool shut down by close(); client is going away too

        fut.add_done_callback(_done)

    def close(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: close alone does not wake a thread
            # blocked in accept(), so the join below would eat its full
            # timeout (measured 5s per server teardown)
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # not connected / already closed — fine
        try:
            self._sock.close()
        except OSError:
            pass
        # kill live connections too: a closed server must stop SERVING,
        # not just stop accepting (their blocked recvs need the shutdown
        # wake-up just like the listener's accept)
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._accept_thread.join(timeout=5)
        self._pool.shutdown(wait=False, cancel_futures=True)


# ------------------------------------------------------------------- client
class ReplicaClient:
    """Multiplexing client for one remote replica.

    Thread-safe: any number of coordinator threads may call
    get_scheduling_decision concurrently; frames interleave on one socket
    and a reader thread resolves the per-id futures. A dead connection
    fails all in-flight requests with BackendError (the DecisionClient
    stack above retries / falls back / trips the breaker exactly as it
    would for a local backend fault).

    Connection lifecycle: LAZY and SELF-HEALING. The first submit dials;
    a dead/never-up replica surfaces as a fast BackendError per decision
    (absorbed by retry/fallback upstream — the coordinator must not crash
    because a worker is still loading weights), and every later submit
    re-dials, so a restarted worker heals without restarting the
    coordinator."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 10.0,
                 request_timeout_s: float = 60.0,
                 reconnect_base_s: float = 0.05,
                 reconnect_cap_s: float = 2.0) -> None:
        self.addr = f"{host}:{port}"
        self._host, self._port = host, port
        self.connect_timeout_s = connect_timeout_s
        self.request_timeout_s = request_timeout_s
        # Reconnect discipline: exponential backoff with jitter. Without
        # it, a worker restarting mid-stream eats one blocking dial
        # (connect_timeout_s each) PER in-flight decision retry — a
        # coordinator-side stall storm — and a fleet of coordinators
        # re-dialing in lockstep thundering-herds the worker the moment
        # it binds its socket. Failed dials open a fail-fast window
        # (decisions during it raise immediately and ride the upstream
        # retry/fallback stack); the window doubles per consecutive
        # failure up to reconnect_cap_s, jittered to ~U[0.5, 1.0)x so
        # herds decorrelate.
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_cap_s = float(reconnect_cap_s)
        self._dial_failures = 0
        self._next_dial_at = 0.0
        self._rng = random.Random()
        # Chaos seam (chaos/faults.py, seam "wire"): None in production —
        # one attribute read per frame. A chaos harness installs a Seam
        # here to inject resets/drops/dups/delays at the REAL framing
        # layer, below every retry/reconnect defense.
        self.fault_seam = None
        self._sock: socket.socket | None = None
        self._reader: threading.Thread | None = None
        self._conn_lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._ids = itertools.count()
        self._closed = False
        # Batched decision-frame flushing: concurrent submitters enqueue
        # encoded frames here; whoever holds the send lock drains the
        # WHOLE outbox as one vectored sendmsg (_send_frames), and threads
        # whose frames were flushed for them (rid in _flushed) return
        # without a syscall. Opportunistic — no timer, no added latency:
        # a lone frame flushes immediately, a burst's leaders coalesce
        # exactly when they contend.
        self._outbox: deque[tuple[int, bytes, bytes]] = deque()
        self._flushed: set[int] = set()
        self._outbox_lock = threading.Lock()
        # Wire-path counters (wire_stats): persistent-connection reuse and
        # flush batching are measured, not assumed.
        self._wire = {
            "dials": 0,
            "frames_sent": 0,
            "flushes": 0,
            "batched_frames": 0,
            "max_batch": 0,
            "bytes_sent": 0,
        }

    def _ensure_connected(self) -> tuple[socket.socket, threading.Thread]:
        """Dial (or re-dial) the replica. Serialized so concurrent submits
        after a drop produce one reconnect, not a stampede."""
        with self._conn_lock:
            if self._closed:
                raise BackendError(f"replica {self.addr} client closed")
            if self._sock is not None and (
                self._reader is not None and self._reader.is_alive()
            ):
                return self._sock, self._reader
            # previous socket (if any) is dead: drop it and re-dial
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            now = time.monotonic()
            if self._dial_failures and now < self._next_dial_at:
                # fail-fast window after a failed dial: don't pay another
                # blocking connect (or hammer a restarting worker) until
                # the backoff expires
                raise BackendError(
                    f"replica {self.addr} unreachable "
                    f"(reconnect backing off "
                    f"{self._next_dial_at - now:.2f}s after "
                    f"{self._dial_failures} failed dial(s))"
                )
            try:
                sock = socket.create_connection(
                    (self._host, self._port), self.connect_timeout_s
                )
            except OSError as exc:
                self._dial_failures += 1
                if self._dial_failures >= 2:
                    # the FIRST failure keeps the historical contract (the
                    # very next submit may re-dial immediately — a worker
                    # that just finished binding its socket heals with
                    # zero added latency); only repetition opens a window
                    delay = min(
                        self.reconnect_cap_s,
                        self.reconnect_base_s
                        * (2 ** min(self._dial_failures - 2, 16)),
                    )
                    self._next_dial_at = now + delay * (
                        0.5 + 0.5 * self._rng.random()
                    )
                raise BackendError(
                    f"replica {self.addr} unreachable: {exc}"
                ) from exc
            self._dial_failures = 0
            self._next_dial_at = 0.0
            # create_connection leaves its timeout ON THE SOCKET: the
            # reader would then die on any response slower than
            # connect_timeout_s (e.g. a first decision paying a jit
            # compile). Per-request deadlines are enforced at
            # fut.result(request_timeout_s); the socket itself blocks
            # indefinitely — with TCP KEEPALIVE on, so a HALF-OPEN peer
            # (host preempted without FIN/RST) eventually kills the
            # reader and the next submit re-dials instead of the reader
            # blocking in recv forever.
            sock.settimeout(None)
            _set_nodelay(sock)
            with self._outbox_lock:
                self._wire["dials"] += 1
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
                if hasattr(socket, "TCP_KEEPIDLE"):
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPIDLE, 30)
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPINTVL, 10)
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPCNT, 3)
            except OSError:
                pass  # keepalive is best-effort hardening
            self._sock = sock
            reader = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name=f"replica-client-{self._port}",
            )
            self._reader = reader
            reader.start()
            return sock, reader

    def _mark_suspect(self, sock: socket.socket) -> None:
        """A request timed out: the connection may be half-open (peer gone
        without FIN/RST — keepalive takes ~minutes). Shut the socket so the
        reader dies, in-flight futures fail fast, and the next submit
        re-dials; if the replica was merely slow, the re-dial is cheap.

        `sock` is the connection the timed-out request was SUBMITTED on:
        if another thread already re-dialed (self._sock replaced), shutting
        down the current socket would spuriously kill a healthy connection
        and every request in flight on it."""
        with self._conn_lock:
            if sock is not self._sock:
                return  # stale connection already replaced; nothing to kill
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                resp = _recv_frame(sock)
                if resp is None:
                    break
                with self._pending_lock:
                    fut = self._pending.pop(resp.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except Exception as exc:  # OSError, desync, MAX_FRAME BackendError…
            # ANY reader death must fall through to the in-flight-failure
            # sweep below — a narrower catch once let a BackendError from
            # the frame-size check skip it, leaving callers to block out
            # their full request timeout with no error ever surfaced.
            if not self._closed:
                logger.warning("replica client %s reader died: %r", self.addr, exc)
        # connection is gone: fail everything in flight (the next submit
        # re-dials via _ensure_connected)
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    BackendError(f"replica {self.addr} connection lost")
                )

    def _flush_frames(
        self,
        sock: socket.socket,
        rid: int,
        frames: list[tuple[bytes, bytes]],
    ) -> None:
        """Put request `rid`'s encoded frames on the wire through the
        shared outbox. The holder of the send lock drains EVERYTHING
        queued as one vectored write, so a burst's concurrent decision
        frames coalesce into one syscall exactly when they contend —
        and a thread that finds its frames already flushed returns
        without touching the socket.

        Send failure semantics: frames the failing flush carried for
        OTHER requests fail through their futures (indistinguishable
        from a reset-after-send, which the reader sweep also produces);
        the flusher's own request raises, matching the historical
        single-frame contract."""
        with self._outbox_lock:
            for header, payload in frames:
                self._outbox.append((rid, header, payload))
        while True:
            with self._outbox_lock:
                if rid in self._flushed:
                    self._flushed.discard(rid)
                    return
            with self._send_lock:
                with self._outbox_lock:
                    batch = list(self._outbox)
                    self._outbox.clear()
                if not batch:
                    continue  # flushed by the previous holder; re-check
                mine = any(r == rid for r, _, _ in batch)
                # Re-resolve the LIVE socket at flush time: the batch may
                # carry frames enqueued against a connection that healed
                # while this thread waited on the send lock — writing
                # them to the stale captured socket would spuriously fail
                # healthy requests. (If no live socket exists the stale
                # one fails exactly as a dead connection should.)
                with self._conn_lock:
                    live = self._sock or sock
                try:
                    _send_frames(live, [(h, p) for _, h, p in batch])
                except OSError as exc:
                    with self._pending_lock:
                        failed = [
                            self._pending.pop(r, None)
                            for r, _, _ in batch
                            if r != rid
                        ]
                    for fut in failed:
                        if fut is not None and not fut.done():
                            fut.set_exception(BackendError(
                                f"replica {self.addr} send failed: {exc}"
                            ))
                    with self._outbox_lock:
                        for r, _, _ in batch:
                            if r != rid:
                                self._flushed.add(r)
                    if mine:
                        raise
                    continue
                with self._outbox_lock:
                    for r, _, _ in batch:
                        self._flushed.add(r)
                    self._wire["flushes"] += 1
                    self._wire["frames_sent"] += len(batch)
                    if len(batch) > 1:
                        self._wire["batched_frames"] += len(batch)
                    self._wire["max_batch"] = max(
                        self._wire["max_batch"], len(batch)
                    )
                    self._wire["bytes_sent"] += sum(
                        len(h) + len(p) for _, h, p in batch
                    )

    def wire_stats(self) -> dict:
        """Copy of the wire-path counters: dials (persistent-connection
        reuse shows here — a healthy client dials once per connection
        lifetime, not per frame), frames vs flushes (batching ratio),
        bytes."""
        with self._outbox_lock:
            return dict(self._wire)

    def _submit_frame(
        self, payload: dict
    ) -> tuple[int, Future, socket.socket]:
        """Allocate an id, register the pending future, and send
        `payload` (id added) — THE single copy of the registration/send/
        reader-death protocol, shared by decisions and prewarms so a fix
        to its subtleties can never drift between them."""
        sock, reader = self._ensure_connected()
        fault = None
        if self.fault_seam is not None:
            pod = payload.get("pod")
            key = pod.get("name") if isinstance(pod, dict) else payload.get("op")
            fault_delay = self.fault_seam.delay_s(key=key)
            if fault_delay > 0:
                time.sleep(fault_delay)  # graftlint: ok[raw-clock] — chaos-injected wire latency; inert (seam is None) in production
            for kind in ("reset", "drop", "dup"):
                if self.fault_seam.should(kind, key=key) is not None:
                    fault = kind
                    break
        rid = next(self._ids)
        fut: Future = Future()
        with self._pending_lock:
            if self._closed:
                raise BackendError(f"replica {self.addr} client closed")
            self._pending[rid] = fut
        try:
            # drop: frame never leaves — the caller times out. reset: the
            # connection dies before the response could ever land — the
            # frame is withheld too, because "sent, then reset" would race
            # the server's reply against the shutdown and the winner would
            # be thread timing (chaos runs must be deterministic); from
            # the caller the two shapes are indistinguishable either way.
            if fault not in ("drop", "reset"):
                frames = [_encode_frame({"id": rid, **payload})]
                if fault == "dup":
                    # duplicate frame, same id: the server serves it
                    # twice and the second response must be a no-op
                    # at the client (pending entry already popped)
                    frames.append(_encode_frame({"id": rid, **payload}))
                self._flush_frames(sock, rid, frames)
        except OSError as exc:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise BackendError(f"replica {self.addr} send failed: {exc}") from exc
        if fault == "reset":
            # mid-decision connection reset: the reader's fail-everything
            # sweep and the next submit's re-dial are the paths under test
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if not reader.is_alive():
            # TOCTOU guard: the reader may have died (and run its
            # fail-everything sweep) BETWEEN the liveness check and our
            # future registration — a first write after FIN can land in
            # the send buffer without EPIPE, leaving this future orphaned
            # with nobody to resolve it. Fail it ourselves.
            with self._pending_lock:
                self._pending.pop(rid, None)
            if not fut.done():
                fut.set_exception(
                    BackendError(f"replica {self.addr} connection lost")
                )
        return rid, fut, sock

    def _submit(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics],
        work: str | None = None,
    ) -> tuple[int, Future, socket.socket]:
        payload = {
            "pod": pod_to_wire(pod),
            "nodes": [node_to_wire(n) for n in nodes],
        }
        if work is not None:
            # disaggregated-pool tag (fleet/pools.py): lets a decode-role
            # worker refuse misrouted admission work
            payload["work"] = work
        # Deadline budget rides the frame (sched/deadline.py): stamp the
        # REMAINING ms at send time so the worker judges against what the
        # decision actually has left, wire transit included.
        remaining = deadline_mod.remaining_ms()
        if remaining is not None:
            payload["deadline_ms"] = round(remaining, 3)
        # Trace propagation: the ambient decision trace's (trace_id,
        # span_id) rides the frame so the worker's spans stitch into ONE
        # cross-host tree (ReplicaServer returns them in the response).
        wire_trace = spans.wire_context()
        if wire_trace is not None:
            payload["trace"] = wire_trace
        return self._submit_frame(payload)

    def prewarm_prefix(self, nodes: Sequence[NodeMetrics]) -> Future:
        """Forward an advisory prefix install to the worker's backend
        (engine/local.prewarm_prefix over the wire). The future resolves
        bool for an ANSWERED advisory (True installed / False dropped —
        both mean the worker is alive) and raises BackendError on
        TRANSPORT failure (connect/send/reader-death/deadline) — the
        distinction FanoutBackend's health gating needs: drops are
        healthy, transport failures feed the cooldown. Deadline-bounded
        by request_timeout_s so a worker that accepts the frame but never
        replies (engine stuck in a long compile) cannot wedge this future
        — or the scheduler's _prewarm_last signature — forever."""
        out: Future = Future()
        try:
            rid, fut, _sock = self._submit_frame({
                "op": "prewarm",
                "nodes": [node_to_wire(n) for n in nodes],
            })
        except Exception as exc:
            out.set_exception(
                BackendError(f"replica {self.addr} prewarm: {exc}")
            )
            return out

        def _expire() -> None:
            self._drop(rid)
            if not out.done():
                out.set_exception(
                    BackendError(
                        f"replica {self.addr} prewarm unanswered after "
                        f"{self.request_timeout_s}s"
                    )
                )

        timer = threading.Timer(self.request_timeout_s, _expire)
        timer.daemon = True

        def _done(f) -> None:
            timer.cancel()
            if out.done():
                return
            try:
                resp = f.result()
                out.set_result(bool(resp.get("ok")))
            except Exception as exc:
                out.set_exception(
                    BackendError(f"replica {self.addr} prewarm: {exc}")
                )

        fut.add_done_callback(_done)
        timer.start()
        return out

    def rollout_swap(self, version: int, timeout_s: float | None = None) -> dict:
        """Ask the worker to hot-swap its backend to a registry version
        (ReplicaServer swap_fn). BLOCKING — the canary controller staggers
        replicas one at a time and needs this replica's verdict before
        touching the next (rollout/canary.staggered_swap). Returns the
        server's {"ok", "detail"|"error"} payload; transport failures raise
        BackendError. `timeout_s` defaults to request_timeout_s — raise it
        for donate-mode swaps whose restore runs inside the pause."""
        rid, fut, sock = self._submit_frame({
            "op": "rollout_swap", "version": int(version),
        })
        try:
            resp = fut.result(
                timeout=self.request_timeout_s if timeout_s is None else timeout_s
            )
        except FuturesTimeout as exc:
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} swap timed out"
            ) from exc
        return {k: v for k, v in resp.items() if k != "id"}

    def telemetry_pull(
        self,
        since_seq: int = 0,
        max_traces: int | None = None,
        max_bytes: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        """Pull this worker's telemetry payload (stats tree with embedded
        histogram buckets, flight-recorder slice since `since_seq`,
        sampler ring — observability/fleetview.build_telemetry shape).
        BLOCKING, like rollout_swap: the aggregator drives one bounded
        pull per source per round, and a dead worker must surface as a
        BackendError the aggregator can mark stale on, not a hang."""
        payload: dict[str, Any] = {
            "op": "telemetry_pull", "since": int(since_seq),
        }
        if max_traces is not None:
            payload["max_traces"] = int(max_traces)
        if max_bytes is not None:
            payload["max_bytes"] = int(max_bytes)
        rid, fut, sock = self._submit_frame(payload)
        try:
            resp = fut.result(
                timeout=self.request_timeout_s if timeout_s is None else timeout_s
            )
        except FuturesTimeout as exc:
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} telemetry pull timed out"
            ) from exc
        if "stats" not in resp:
            raise BackendError(
                f"replica {self.addr}: "
                f"{resp.get('error', 'malformed telemetry response')}"
            )
        return {k: v for k, v in resp.items() if k != "id"}

    def _resolve(self, resp: dict) -> SchedulingDecision:
        if "decision" in resp:
            remote_spans = resp.get("spans")
            if remote_spans:
                trace = spans.current_trace()
                if trace is not None:
                    # merge_remote_spans drops spans whose trace id does
                    # not match — a desynced frame cannot pollute the tree
                    trace.merge_remote_spans(remote_spans)
            return decision_from_wire(resp["decision"])
        if resp.get("kind") == "infeasible":
            raise NoFeasibleNodeError(resp.get("error", ""))
        if resp.get("kind") == "deadline":
            # the worker refused an expired frame: degrade, don't retry
            # (and don't count a breaker failure — sched/client.py)
            raise DeadlineExceededError(resp.get("error", ""))
        raise BackendError(
            f"replica {self.addr}: {resp.get('error', 'unknown failure')}"
        )

    def _drop(self, rid: int) -> None:
        with self._pending_lock:
            self._pending.pop(rid, None)

    def _resolve_batch(
        self, resp: dict
    ) -> list["SchedulingDecision | Exception"]:
        """Positional per-pod outcomes of a decide_batch: a decision, a
        NoFeasibleNodeError, or a BackendError — returned, not raised,
        so one bad pod never fails its batchmates."""
        if "results" not in resp:
            raise BackendError(
                f"replica {self.addr}: {resp.get('error', 'malformed batch response')}"
            )
        out: list[SchedulingDecision | Exception] = []
        for entry in resp["results"]:
            if "decision" in entry:
                out.append(decision_from_wire(entry["decision"]))
            elif entry.get("kind") == "infeasible":
                out.append(NoFeasibleNodeError(entry.get("error", "")))
            elif entry.get("kind") == "deadline":
                out.append(DeadlineExceededError(entry.get("error", "")))
            else:
                out.append(BackendError(
                    f"replica {self.addr}: "
                    f"{entry.get('error', 'unknown failure')}"
                ))
        return out

    def _submit_batch(
        self, pods: Sequence[PodSpec], nodes: Sequence[NodeMetrics],
        work: str | None,
    ) -> tuple[int, Future, socket.socket]:
        payload = {
            "op": "decide_batch",
            "pods": [pod_to_wire(p) for p in pods],
            "nodes": [node_to_wire(n) for n in nodes],
        }
        if work is not None:
            payload["work"] = work
        # the batch shares one deadline budget, same stamp as _submit —
        # without it prepacked admission would silently opt out of the
        # degradation ladder
        remaining = deadline_mod.remaining_ms()
        if remaining is not None:
            payload["deadline_ms"] = round(remaining, 3)
        return self._submit_frame(payload)

    def get_scheduling_decisions_batch(
        self, pods: Sequence[PodSpec], nodes: Sequence[NodeMetrics],
        work: str | None = None,
    ) -> list["SchedulingDecision | Exception"]:
        """Prepacked admission: ship `pods` (sharing ONE snapshot) as a
        single decide_batch frame; the worker's engine admits them
        together and coalesces them into one prefill wave."""
        rid, fut, sock = self._submit_batch(pods, nodes, work)
        try:
            resp = fut.result(timeout=self.request_timeout_s)
        except FuturesTimeout as exc:
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} batch timed out after "
                f"{self.request_timeout_s}s"
            ) from exc
        return self._resolve_batch(resp)

    async def get_scheduling_decisions_batch_async(
        self, pods: Sequence[PodSpec], nodes: Sequence[NodeMetrics],
        work: str | None = None,
    ) -> list["SchedulingDecision | Exception"]:
        import asyncio

        rid, fut, sock = self._submit_batch(pods, nodes, work)
        try:
            resp = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=self.request_timeout_s
            )
        except (TimeoutError, asyncio.TimeoutError) as exc:
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} batch timed out after "
                f"{self.request_timeout_s}s"
            ) from exc
        return self._resolve_batch(resp)

    def get_scheduling_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics],
        work: str | None = None,
    ) -> SchedulingDecision:
        rid, fut, sock = self._submit(pod, nodes, work)
        try:
            resp = fut.result(timeout=self.request_timeout_s)
        except FuturesTimeout as exc:
            # drop the pending entry (it would otherwise leak for the
            # connection's lifetime), mark the connection suspect (a
            # half-open peer would otherwise stall EVERY later request by
            # the full timeout), and surface the documented failure type
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} timed out after {self.request_timeout_s}s"
            ) from exc
        return self._resolve(resp)

    async def get_scheduling_decision_async(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics],
        work: str | None = None,
    ) -> SchedulingDecision:
        """Natively-async variant (DecisionClient prefers it): awaits the
        wire future without holding a worker thread, so a burst's leaders
        fan out to replicas without being capped by the to_thread pool."""
        import asyncio

        rid, fut, sock = self._submit(pod, nodes, work)
        try:
            resp = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=self.request_timeout_s
            )
        except (TimeoutError, asyncio.TimeoutError) as exc:
            self._drop(rid)
            self._mark_suspect(sock)
            raise BackendError(
                f"replica {self.addr} timed out after {self.request_timeout_s}s"
            ) from exc
        return self._resolve(resp)

    def close(self) -> None:
        with self._pending_lock:
            self._closed = True
        with self._conn_lock:
            sock, reader = self._sock, self._reader
            self._sock = None
        if sock is not None:
            try:
                # shutdown wakes the reader blocked in recv (close alone
                # does not — it parked the join below for its full timeout)
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if reader is not None:
            reader.join(timeout=5)


# ------------------------------------------------------------------ fan-out
class _ReplicaHealth:
    """Per-replica dispatch state: in-flight count, latency EMA, failure
    cooldown. Mutated under the owning FanoutBackend's lock."""

    __slots__ = ("inflight", "ema_s", "failures", "cooldown_until", "probing")

    def __init__(self) -> None:
        self.inflight = 0
        self.ema_s = 0.0  # 0 = no sample yet (treated as fast/unknown)
        self.failures = 0
        self.cooldown_until = 0.0
        # set when the current request is a starvation probe: its sample
        # REPLACES the (stale) EMA instead of blending — the whole point
        # of the probe is re-measurement
        self.probing = False


class FanoutBackend:
    """Health-aware decision dispatch across [local backend, replicas...].

    Sits at the DecisionBackend seam, below cache/single-flight: only
    leader decisions reach it, so replica count multiplies exactly the
    model compute (shared-prefix economics hold on every replica
    independently — each re-prefills the burst's snapshot prefix once).

    Dispatch is weighted least-load, not round-robin (VERDICT r4 weak #7:
    one slow or half-dead replica round-robined 1/N of every burst into
    its queue and inflated the whole burst's tail). Each replica carries
    (in-flight count, latency EMA, failure cooldown); a request routes to
    the replica minimizing (inflight + 1) * ema_latency — so a 10x-slower
    replica organically receives ~1/10 of the traffic instead of 1/N —
    and a replica that throws enters exponential cooldown (capped) so a
    dead host drops out of rotation entirely until it heals. Failures
    still surface as the BackendError the retry/breaker/fallback stack
    above already handles."""

    COOLDOWN_BASE_S = 0.5
    COOLDOWN_CAP_S = 30.0
    EMA_ALPHA = 0.2
    # A replica not routed to for PROBE_IDLE_S gets one probe request: the
    # EMA only updates on routed requests, so without re-probing one
    # transient slow sample (cold compile, GC pause) would starve a
    # healthy replica forever. Two gates bound the probe cost:
    # - TIME (idle >= PROBE_IDLE_S): pick-counted probes at burst rates
    #   would re-route a slow replica's full latency into the burst every
    #   N decisions (~30% capacity at 400/s measured);
    # - COUNT (>= PROBE_EVERY_PICKS dispatches since the last probe):
    #   under SPARSE traffic (inter-arrival > PROBE_IDLE_S) the time gate
    #   alone would make every request a probe, degenerating dispatch to
    #   alternation — the count gate caps probes at 1/PROBE_EVERY_PICKS
    #   of traffic regardless of rate.
    PROBE_IDLE_S = 5.0
    PROBE_EVERY_PICKS = 8
    # Replicas slower than SLOW_EXCLUDE_RATIO x the fastest EMA receive
    # no cost-picked traffic at all (probes only): decisions are latency-
    # sensitive, and inflight pressure on the fast replicas would
    # otherwise leak band-tied picks onto a 10x replica exactly at burst
    # peaks — where its full latency lands on the burst's tail.
    SLOW_EXCLUDE_RATIO = 4.0

    def __init__(
        self,
        replicas: Sequence[Any],
        clock: "Callable[[], float]" = time.monotonic,
    ) -> None:
        if not replicas:
            raise ValueError("FanoutBackend needs at least one replica")
        self.replicas = list(replicas)
        self.routed = [0] * len(self.replicas)
        self._health = [_ReplicaHealth() for _ in self.replicas]
        self._lock = threading.Lock()
        self._rr = itertools.count()  # tiebreak rotation among equals
        # Injectable time source: every probe-window / cooldown / EMA
        # judgment reads THIS clock, so tests can advance time explicitly
        # instead of racing real sleeps on a loaded host (VERDICT r5 #6).
        self._clock = clock
        self._last_routed_t = [self._clock()] * len(self.replicas)
        self._picks_total = 0
        self._last_probe_pick = 0

    # ------------------------------------------------------------- dispatch
    def _pick(self) -> int:
        """Weighted least-load choice; replicas in failure cooldown are
        skipped unless ALL are cooling down (then least-bad is used — a
        decision must still be attempted so the upstream stack can fall
        back on a real error, not on dispatch refusal)."""
        now = self._clock()
        rotate = next(self._rr)
        with self._lock:
            candidates = [
                i for i, h in enumerate(self._health)
                if h.cooldown_until <= now
            ]
            if not candidates:
                candidates = list(range(len(self.replicas)))
            # starvation probe: a candidate idle past PROBE_IDLE_S gets
            # this request so its EMA can recover — at most one probe per
            # PROBE_EVERY_PICKS dispatches (see class comment)
            self._picks_total += 1
            starved = [
                i for i in candidates
                if now - self._last_routed_t[i] >= self.PROBE_IDLE_S
            ]
            if starved and (
                self._picks_total - self._last_probe_pick
                >= self.PROBE_EVERY_PICKS
            ):
                i = min(starved, key=lambda j: self._last_routed_t[j])
                self._last_probe_pick = self._picks_total
                self._last_routed_t[i] = now
                self._health[i].inflight += 1
                self._health[i].probing = True
                self.routed[i] += 1
                return i

            # slow exclusion: drop way-slower replicas from the cost pick
            # (probes above keep their EMAs fresh so they can rejoin)
            min_ema = min(
                (h.ema_s for h in self._health if h.ema_s), default=0.0
            )
            if min_ema:
                fast_enough = [
                    i for i in candidates
                    if not self._health[i].ema_s
                    or self._health[i].ema_s
                    <= self.SLOW_EXCLUDE_RATIO * min_ema
                ]
                if fast_enough:
                    candidates = fast_enough

            def cost(i: int) -> tuple:
                h = self._health[i]
                # unknown latency ranks as the fastest observed (optimistic
                # first sample). The load score is BANDED (~25% classes):
                # µs-level EMA noise between equal replicas must not make
                # one a permanent winner under sequential traffic — within
                # a band the rotation index shares work evenly.
                ema = h.ema_s or min_ema
                score = (h.inflight + 1) * (ema or 1e-6)
                band = int(math.log(score) / math.log(1.25))
                return (band, (i + rotate) % len(self.replicas))

            i = min(candidates, key=cost)
            self._last_routed_t[i] = now
            self._health[i].inflight += 1
            self.routed[i] += 1
            return i

    def _record(
        self,
        i: int,
        elapsed_s: float | None,
        failed: bool,
        adjust_inflight: bool = True,
    ) -> None:
        with self._lock:
            h = self._health[i]
            if adjust_inflight:
                h.inflight = max(0, h.inflight - 1)
            if failed:
                self._note_failure_locked(h)
            else:
                h.failures = 0
                h.cooldown_until = 0.0
                if elapsed_s is not None:
                    h.ema_s = (
                        elapsed_s if (h.ema_s == 0.0 or h.probing)
                        else (1 - self.EMA_ALPHA) * h.ema_s
                        + self.EMA_ALPHA * elapsed_s
                    )
            h.probing = False

    def _note_failure_locked(self, h: _ReplicaHealth) -> None:
        """Exponential-backoff cooldown bump (caller holds self._lock)."""
        h.failures += 1
        backoff = min(
            self.COOLDOWN_CAP_S,
            self.COOLDOWN_BASE_S * (2 ** min(h.failures - 1, 8)),
        )
        h.cooldown_until = self._clock() + backoff

    def _record_advisory_failure(self, i: int) -> None:
        """Prewarm TRANSPORT failure: feed the cooldown, and ONLY the
        cooldown. Deliberately not _record (ADVICE round 5): an advisory
        completion must not reset `failures`/`cooldown_until` on success —
        a healthy prewarm answer from a replica mid-cooldown would
        re-admit it before its decision backoff expired — and must not
        clear `probing`, which belongs to an in-flight DECISION probe the
        prewarm knows nothing about."""
        with self._lock:
            self._note_failure_locked(self._health[i])

    def prewarm_prefix(self, nodes: Sequence[NodeMetrics]):
        """Fan the advisory prefix install out to every replica that
        supports it AND is not in failure cooldown (shared-prefix
        economics hold per replica — each one pays its own cluster-state
        prefill on the first leader otherwise).

        Health integration: a TRANSPORT failure (connect/send/deadline —
        the replica client raises) feeds the same exponential cooldown
        decisions use, so a black-holed worker costs at most one blocking
        dial per cooldown expiry instead of one per prewarm tick. Any
        ANSWERED advisory (installed or dropped) is health-neutral: it
        neither clears decision failure state nor touches an in-flight
        probe (_record_advisory_failure). Cooling replicas are skipped
        outright.

        Returns None when no replica supports prewarming (disables the
        scheduler's prewarm loop), else a Future resolving True iff every
        replica that was actually forwarded to installed — False (any
        drop, any failure, or everyone cooling) re-arms the loop's retry
        on its next idle tick."""
        now = self._clock()
        futs: list[tuple[int, Future]] = []
        supported = 0
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "prewarm_prefix", None)
            if fn is None:
                continue
            supported += 1
            with self._lock:
                cooling = self._health[i].cooldown_until > now
            if cooling:
                continue
            futs.append((i, fn(nodes)))
        if not supported:
            return None
        out: Future = Future()
        if not futs:  # all supported replicas cooling: retry next tick
            out.set_result(False)
            return out
        state = {"left": len(futs), "ok": True}
        lock = threading.Lock()

        def _done(i: int, f: Future) -> None:
            try:
                ok = bool(f.result())
                failed = False
            except Exception:
                ok, failed = False, True
            if failed:
                # failure path only: successes (installed OR dropped) are
                # advisory and must not touch decision health state
                self._record_advisory_failure(i)
            with lock:
                state["ok"] &= ok
                state["left"] -= 1
                finished = state["left"] == 0
            if finished and not out.done():
                out.set_result(state["ok"])

        for i, f in futs:
            f.add_done_callback(lambda fut, i=i: _done(i, fut))
        return out

    def get_scheduling_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        i = self._pick()
        start = self._clock()
        failed = False
        elapsed = None
        # accounting in finally: a BaseException (e.g. asyncio
        # cancellation propagating through to_thread) must still release
        # the inflight slot — a leak here permanently skews dispatch away
        # from a healthy replica. Cancellation records neither latency nor
        # failure: it is not the replica's fault.
        try:
            decision = self.replicas[i].get_scheduling_decision(pod, nodes)
            elapsed = self._clock() - start
            return decision
        except NoFeasibleNodeError:
            # a correct "no" is a healthy, fast answer — not a failure
            elapsed = self._clock() - start
            raise
        except Exception:
            failed = True
            raise
        finally:
            self._record(i, elapsed, failed=failed)

    async def get_scheduling_decision_async(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        """Async routing: without this, wrapping a backend in FanoutBackend
        would hide the replicas' native async paths from DecisionClient and
        throttle every leader through the default to_thread pool (~32
        threads) — the exact bottleneck the async path exists to avoid."""
        import asyncio

        i = self._pick()
        replica = self.replicas[i]
        start = self._clock()
        failed = False
        elapsed = None
        try:
            fn = getattr(replica, "get_scheduling_decision_async", None)
            if fn is not None:
                decision = await fn(pod, nodes)
            else:
                decision = await asyncio.to_thread(
                    replica.get_scheduling_decision, pod, nodes
                )
            elapsed = self._clock() - start
            return decision
        except NoFeasibleNodeError:
            elapsed = self._clock() - start
            raise
        except Exception:
            failed = True
            raise
        finally:
            # finally, not except: CancelledError must release the
            # inflight slot (without a latency sample or a cooldown)
            self._record(i, elapsed, failed=failed)

    def get_stats(self) -> dict:
        with self._lock:
            stats: dict[str, Any] = {
                "fanout_routed": list(self.routed),
                "fanout_ema_ms": [
                    round(h.ema_s * 1000.0, 2) for h in self._health
                ],
                "fanout_cooling": [
                    h.cooldown_until > self._clock() for h in self._health
                ],
            }
        local = self.replicas[0]
        if hasattr(local, "get_stats"):
            stats.update(local.get_stats())
        return stats

    def close(self) -> None:
        for r in self.replicas:
            if hasattr(r, "close"):
                r.close()
