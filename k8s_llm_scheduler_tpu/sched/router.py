"""Per-decision routing between the sharded big arm and the distilled
fast arm.

The north-star serving stack (BASELINE config 3) runs TWO model tiers:
a 70B-class decision LLM tensor-parallel over the ICI mesh
(engine/sharded/), and a scheduler-specialized small checkpoint
distilled from it (train/distill.py + the rollout registry). The big
arm is slow and smart; the fast arm is cheap and right for the easy
mass of decisions. This module is the seam that picks an arm PER
DECISION — not per deployment — so the hybrid can spend the 70B budget
only where it pays.

Decision classes (classify_decision):

- **deadline budget** (sched/deadline.py ambient DeadlineBudget): a
  decision whose remaining budget cannot cover the big arm's typical
  latency goes fast — a late great answer loses to an on-time good one
  (the degradation-ladder premise, applied one rung earlier);
- **pod constraint complexity**: selectors, tolerations, affinity and
  priority raise the stakes — constrained pods are where the big model
  measurably beats the small one (learn/ weakness mining shows the
  fast tier's losses concentrate there), so complexity >= threshold
  routes big;
- **cache tier**: a cluster snapshot the big arm has never prefilled
  is a full prefix prefill away from its first token; when the budget
  cannot also absorb that cold-start, the decision goes fast and the
  router fires the big arm's prefix prewarm in the background so the
  NEXT decision on this snapshot finds it warm.

The hybrid is not assumed better — it is GATED (run_hybrid_gate): a
seeded arena run (sim/arena.py) scores big-alone, fast-alone, and the
routed hybrid on the canary gate's axes (spread down, constraint
satisfaction up, bound fraction up; rollout/canary.GateConfig
tolerances), and the hybrid must beat or match BOTH arms alone.

`RoutedBackend` implements the DecisionBackend protocol, so it slots
under sched/client.DecisionClient (cache, single-flight, breaker,
degradation ladder all stack on top) and inside fleet pools exactly
like any local or remote backend.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
from collections import OrderedDict
from collections.abc import Sequence
from typing import Any, Callable

from k8s_llm_scheduler_tpu.core.cache import _nodes_digest
from k8s_llm_scheduler_tpu.engine.backend import (
    DecisionBackend,
    NoFeasibleNodeError,
)
from k8s_llm_scheduler_tpu.sched.deadline import (
    DeadlineExceededError,
    current_budget,
)
from k8s_llm_scheduler_tpu.types import (
    NodeMetrics,
    PodSpec,
    SchedulingDecision,
)

logger = logging.getLogger(__name__)

ROUTE_BIG = "big"
ROUTE_FAST = "fast"

# Exceptions that are VERDICTS, not arm failures: failing over to the
# other arm on these would re-ask a question that was already answered
# (no node fits) or already out of time.
_NO_FAILOVER = (NoFeasibleNodeError, DeadlineExceededError)


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Routing thresholds. Defaults suit the 1B-operating-point latency
    envelope (BENCH notes); config block `router` overrides them."""

    # Remaining deadline budget (ms) below which the big arm is not
    # attempted — covers its typical warm decision latency.
    big_min_budget_ms: float = 120.0
    # Additional budget (ms) a COLD snapshot must have on top of
    # big_min_budget_ms to absorb the big arm's prefix prefill.
    big_cold_extra_ms: float = 250.0
    # Constraint-complexity score at or above which a pod routes big
    # (see pod_complexity).
    complexity_threshold: int = 2
    # With no ambient deadline budget at all, assume this much headroom
    # (offline/batch callers — the arena, replayed traces).
    no_budget_assume_ms: float = 1000.0
    # Snapshot digests remembered as warm on the big arm (LRU bound).
    warm_snapshots: int = 64
    # Fire the big arm's prefix prewarm when a cold snapshot forces a
    # fast route, so the next decision on it can go big.
    prewarm_on_cold: bool = True


def pod_complexity(pod: PodSpec) -> int:
    """Constraint-complexity score: how much scheduling judgment this
    pod demands. Each selector term, toleration, and affinity rule adds
    one; a nonzero priority adds one (preemption-adjacent placements
    are the expensive mistakes)."""
    score = len(pod.node_selector) + len(pod.tolerations)
    score += len(getattr(pod, "affinity_rules", None) or {})
    if getattr(pod, "priority", 0):
        score += 1
    return score


class _WarmDigests:
    """LRU set of snapshot digests the big arm has served (= its prefix
    cache plausibly holds them). Thread-safe: the router is called from
    the scheduler loop's executor threads."""

    def __init__(self, cap: int) -> None:
        self._cap = max(1, int(cap))
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()

    def is_warm(self, digest: bytes) -> bool:
        with self._lock:
            if digest in self._seen:
                self._seen.move_to_end(digest)
                return True
            return False

    def note(self, digest: bytes) -> None:
        with self._lock:
            self._seen[digest] = None
            self._seen.move_to_end(digest)
            while len(self._seen) > self._cap:
                self._seen.popitem(last=False)


def classify_decision(
    pod: PodSpec,
    nodes: Sequence[NodeMetrics],
    *,
    policy: RouterPolicy,
    warm: _WarmDigests,
) -> tuple[str, str]:
    """(arm, reason) for one decision. Pure over its inputs plus the
    ambient deadline budget — the reason string is a stable counter key
    (router stats), not prose."""
    budget = current_budget()
    if budget is not None:
        remaining = budget.remaining_ms()
    else:
        remaining = policy.no_budget_assume_ms
    if remaining < policy.big_min_budget_ms:
        return ROUTE_FAST, "deadline_budget"
    digest = _nodes_digest(nodes)
    cold = not warm.is_warm(digest)
    if cold and remaining < policy.big_min_budget_ms + policy.big_cold_extra_ms:
        return ROUTE_FAST, "cold_snapshot"
    if pod_complexity(pod) >= policy.complexity_threshold:
        return ROUTE_BIG, "constraint_complexity"
    return ROUTE_FAST, "simple_pod"


class RoutedBackend:
    """DecisionBackend (structural, like every backend here) that routes
    each decision between two arms.

    `big` is the sharded tp serving stack; `fast` the distilled small
    checkpoint (both any DecisionBackend — local engine, fleet pool,
    remote client). Failover: if the chosen arm errors (not a
    no-feasible-node / deadline verdict), the other arm answers and the
    failover is counted — a down arm degrades the hybrid to the
    surviving tier instead of the heuristic ladder.
    """

    pool_role = "mixed"

    def __init__(
        self,
        big: DecisionBackend,
        fast: DecisionBackend,
        policy: RouterPolicy | None = None,
        *,
        owned: bool = True,
    ) -> None:
        self.big = big
        self.fast = fast
        self.policy = policy or RouterPolicy()
        self._owned = owned
        self._warm = _WarmDigests(self.policy.warm_snapshots)
        self._lock = threading.Lock()
        self.stats_counters: dict[str, int] = {
            "routed_big": 0,
            "routed_fast": 0,
            "failovers": 0,
            "cold_prewarms": 0,
        }
        self._reasons: dict[str, int] = {}

    # ------------------------------------------------------------ routing
    def _arm(self, name: str) -> DecisionBackend:
        return self.big if name == ROUTE_BIG else self.fast

    def _note_route(self, arm: str, reason: str) -> None:
        with self._lock:
            self.stats_counters[f"routed_{arm}"] += 1
            self._reasons[reason] = self._reasons.get(reason, 0) + 1

    def _route(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> tuple[str, str]:
        arm, reason = classify_decision(
            pod, nodes, policy=self.policy, warm=self._warm
        )
        self._note_route(arm, reason)
        if arm == ROUTE_BIG:
            # The big arm is about to prefill (or re-use) this snapshot:
            # it is warm for every later decision in the burst.
            self._warm.note(_nodes_digest(nodes))
        elif reason == "cold_snapshot" and self.policy.prewarm_on_cold:
            self._fire_big_prewarm(nodes)
        return arm, reason

    def _fire_big_prewarm(self, nodes: Sequence[NodeMetrics]) -> None:
        prewarm = getattr(self.big, "prewarm_prefix", None)
        if prewarm is None:
            return
        try:
            prewarm(nodes)
        except Exception:  # pragma: no cover - advisory path
            logger.debug("big-arm prewarm failed", exc_info=True)
            return
        self._warm.note(_nodes_digest(nodes))
        with self._lock:
            self.stats_counters["cold_prewarms"] += 1

    # ----------------------------------------------------------- sync API
    def get_scheduling_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        arm, _reason = self._route(pod, nodes)
        try:
            return self._arm(arm).get_scheduling_decision(pod, nodes)
        except _NO_FAILOVER:
            raise
        except Exception:
            other = ROUTE_FAST if arm == ROUTE_BIG else ROUTE_BIG
            logger.warning(
                "router: %s arm failed, failing over to %s", arm, other,
                exc_info=True,
            )
            with self._lock:
                self.stats_counters["failovers"] += 1
            return self._arm(other).get_scheduling_decision(pod, nodes)

    def get_scheduling_decisions_batch(
        self, pods: Sequence[PodSpec], nodes: Sequence[NodeMetrics]
    ) -> list[SchedulingDecision]:
        """Split the batch by decision class, ship each sub-batch to its
        arm's batch path (packed admission on a local engine), reassemble
        in submission order."""
        routes = [self._route(pod, nodes)[0] for pod in pods]
        out: list[SchedulingDecision | None] = [None] * len(pods)
        for arm_name in (ROUTE_BIG, ROUTE_FAST):
            idx = [i for i, r in enumerate(routes) if r == arm_name]
            if not idx:
                continue
            arm = self._arm(arm_name)
            sub = [pods[i] for i in idx]
            batch = getattr(arm, "get_scheduling_decisions_batch", None)
            if batch is not None:
                results = batch(sub, nodes)
            else:
                results = [
                    arm.get_scheduling_decision(p, nodes) for p in sub
                ]
            for i, res in zip(idx, results):
                out[i] = res
        return [r for r in out if r is not None] if None in out else out  # type: ignore[return-value]

    # ---------------------------------------------------------- async API
    async def _call_async(
        self, arm: DecisionBackend, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        fn = getattr(arm, "get_scheduling_decision_async", None)
        if fn is not None:
            return await fn(pod, nodes)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, arm.get_scheduling_decision, pod, nodes
        )

    async def get_scheduling_decision_async(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        arm, _reason = self._route(pod, nodes)
        try:
            return await self._call_async(self._arm(arm), pod, nodes)
        except _NO_FAILOVER:
            raise
        except Exception:
            other = ROUTE_FAST if arm == ROUTE_BIG else ROUTE_BIG
            logger.warning(
                "router: %s arm failed (async), failing over to %s",
                arm, other, exc_info=True,
            )
            with self._lock:
                self.stats_counters["failovers"] += 1
            return await self._call_async(self._arm(other), pod, nodes)

    # ----------------------------------------------------------- plumbing
    def prewarm_prefix(self, nodes: Sequence[NodeMetrics]):
        """Prewarm BOTH arms (each maintains its own prefix cache) and
        mark the snapshot warm for routing."""
        res = None
        for arm in (self.big, self.fast):
            fn = getattr(arm, "prewarm_prefix", None)
            if fn is not None:
                res = fn(nodes)
        self._warm.note(_nodes_digest(nodes))
        return res

    def get_stats(self) -> dict[str, Any]:
        with self._lock:
            counters = dict(self.stats_counters)
            reasons = dict(self._reasons)
        stats: dict[str, Any] = {
            "backend": "routed",
            "router": {**counters, "route_reasons": reasons},
        }
        for name, arm in (("big", self.big), ("fast", self.fast)):
            get = getattr(arm, "get_stats", None)
            if get is not None:
                try:
                    stats[name] = get()
                except Exception:  # pragma: no cover - stats best-effort
                    stats[name] = {"error": "stats unavailable"}
        return stats

    def close(self) -> None:
        if not self._owned:
            return
        for arm in (self.big, self.fast):
            closer = getattr(arm, "close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:  # pragma: no cover - teardown best-effort
                    logger.warning("router: arm close failed", exc_info=True)


# ------------------------------------------------------------------ distill
def distill_fast_checkpoint(
    cfg,
    out_dir: str,
    *,
    steps: int = 300,
    seed: int = 0,
    tokenizer_name: str = "numeric",
    registry_dir: str | None = None,
    **train_kwargs: Any,
) -> str:
    """Distill the scheduler-specialized fast tier and return the
    servable checkpoint path.

    Thin veneer over train/distill.train_and_save — the EXISTING
    teacher-pair distillation path — that defaults the knobs the fast
    tier wants (numeric tokenizer, registry publication when a registry
    is given so the checkpoint carries provenance/lineage like every
    other promoted artifact)."""
    from k8s_llm_scheduler_tpu.train.distill import train_and_save

    train_and_save(
        cfg,
        out_dir,
        steps=steps,
        seed=seed,
        tokenizer_name=tokenizer_name,
        registry_dir=registry_dir,
        publish_note=f"router fast tier (distilled, steps={steps})",
        **train_kwargs,
    )
    if registry_dir is not None:
        from k8s_llm_scheduler_tpu.rollout import CheckpointRegistry

        registry = CheckpointRegistry(registry_dir)
        active = registry.active()
        if active is not None:
            return str(registry.get(active).checkpoint_path)
    return out_dir


# --------------------------------------------------------------------- gate
def run_hybrid_gate(
    make_big: Callable[[], Any],
    make_fast: Callable[[], Any],
    make_hybrid: Callable[[], Any],
    gate=None,
) -> dict:
    """Arena-gate the routed hybrid against BOTH arms alone.

    Runs the three stacks over the same seeded scenario (the canary
    gate's scenario shape) and applies the gate's score checks twice:
    hybrid-vs-big and hybrid-vs-fast. The hybrid passes only if it is
    no worse than EITHER arm alone on every axis — the routing policy
    must not buy latency with placement quality.
    """
    from k8s_llm_scheduler_tpu.rollout.canary import GateConfig
    from k8s_llm_scheduler_tpu.sim import ArmSpec, generate_scenario, run_arena
    from k8s_llm_scheduler_tpu.sim.scenarios import ScenarioSpec

    gate = gate or GateConfig()
    spec = ScenarioSpec(
        name="router-gate",
        seed=gate.seed,
        n_nodes=gate.nodes,
        n_pods=gate.pods,
        shapes=gate.shapes,
        arrival="waves",
        n_waves=gate.waves,
        constraint_mix=gate.constraint_mix,
        taint_frac=gate.taint_frac,
        hetero=gate.hetero,
    )
    scenario = generate_scenario(spec)
    report = run_arena(
        scenario,
        [
            ArmSpec(name="big", kind="stack", make=make_big),
            ArmSpec(name="fast", kind="stack", make=make_fast),
            ArmSpec(name="hybrid", kind="stack", make=make_hybrid),
        ],
        wave_timeout_s=gate.wave_timeout_s,
    )
    scores = {name: arm["scores"] for name, arm in report["arms"].items()}
    hyb = scores["hybrid"]

    def axes(baseline: dict) -> dict:
        return {
            "spread": hyb["spread"] <= baseline["spread"] + gate.spread_tolerance,
            "constraint_satisfaction": (
                hyb["constraint_satisfaction"]
                >= baseline["constraint_satisfaction"] - gate.constraint_tolerance
            ),
            "bound_frac": (
                hyb["bound_frac"] >= baseline["bound_frac"] - gate.bound_tolerance
            ),
        }

    checks = {"vs_big": axes(scores["big"]), "vs_fast": axes(scores["fast"])}
    return {
        "pass": all(all(c.values()) for c in checks.values()),
        "checks": checks,
        "scores": scores,
        "seed": gate.seed,
        "scenario_spec": spec.to_dict(),
    }
