"""Cluster-twin simulator & policy arena.

The instrument the BASELINE north star is judged with: seeded scenario
generators (`scenarios.py`) drive the wire-level fake API server
(cluster/wire_fake.py) so the REAL scheduler stack — watch, snapshot,
prompt, cache, breaker, decide, bind — runs end to end; the arena
(`arena.py`) runs the same scenario across decision arms (served LLM,
each core/fallback heuristic, the sim/teacher.py spread-lookahead
reference) and scores the placements; `trace.py` records every run as a
deterministic trace that replays bit-identically and attributes per-wave
latency (snapshot vs admission vs prefill/decode vs bind).
"""

from k8s_llm_scheduler_tpu.sim.arena import (
    ArmSpec,
    HeuristicBackend,
    heuristic_arms,
    run_arena,
    score_placement,
    stub_llm_arm,
    teacher_arm,
)
from k8s_llm_scheduler_tpu.sim.scenarios import (
    ChurnEvent,
    ClusterModel,
    Scenario,
    ScenarioSpec,
    SimNode,
    SimPod,
    generate_scenario,
)
from k8s_llm_scheduler_tpu.sim.trace import (
    build_trace,
    replay_trace,
    save_trace,
    verify_trace,
)

__all__ = [
    "ArmSpec",
    "ChurnEvent",
    "ClusterModel",
    "HeuristicBackend",
    "Scenario",
    "ScenarioSpec",
    "SimNode",
    "SimPod",
    "build_trace",
    "generate_scenario",
    "heuristic_arms",
    "replay_trace",
    "run_arena",
    "save_trace",
    "score_placement",
    "stub_llm_arm",
    "teacher_arm",
]
