"""Policy arena: one scenario, many deciders, comparable scores.

Round-5 VERDICT: the served LLM decider had never been DEMONSTRATED
beating the `resource_balanced` fallback on any placement metric. The
arena is that demonstration instrument. Every arm runs the SAME seeded
scenario (sim/scenarios.py); placements are scored on:

- **spread**: pstdev of fractional pod fills (train/eval.load_spread —
  the metric the decision prompt asks the model to optimize);
- **utilization balance**: pstdev of requested-CPU and requested-memory
  allocation fractions across nodes (fill spread can look perfect while
  one node holds all the fat pods);
- **constraint satisfaction**: fraction of placed pods whose node passes
  selector/taint/affinity predicates (core/validation — 1.0 or the arm
  is breaking K8s contracts);
- **fragmentation**: 1 - (pods of the mean shape that still fit given
  per-node free vectors) / (pods that would fit if free capacity were
  pooled) — stranded-capacity bin-packing waste;
- **bound fraction** and per-wave latency attribution (sim/trace.py).

Two arm modes:
- `stack`: the decider is a DecisionBackend and the scenario runs through
  the REAL pipeline — wire-level fake API server (cluster/wire_fake.py),
  the in-tree kube client's watch/informer/bind paths over real sockets,
  DecisionClient's cache/single-flight/breaker, the scheduler loop.
  Placements are deterministic because decisions are pure per
  (pod shape, settled snapshot) and waves are drained to a barrier.
- `policy`: the decider is a stateful sequential policy (sim/teacher.py)
  replayed over the deterministic ClusterModel — the reference score the
  live arms chase.
"""

from __future__ import annotations

import asyncio
import dataclasses
import statistics
import time
from typing import Any, Callable, Sequence

from k8s_llm_scheduler_tpu.core.fallback import (
    SCORERS,
    fallback_decision,
)
from k8s_llm_scheduler_tpu.core.validation import (
    node_affinity_matches,
    selector_matches,
    tolerates_taints,
)
from k8s_llm_scheduler_tpu.sim.scenarios import (
    SCHEDULER_NAME,
    ClusterModel,
    Scenario,
    SimPod,
    add_pod_to_wire,
    apply_churn_to_wire,
    apply_topology,
)
from k8s_llm_scheduler_tpu.sim.teacher import SpreadLookaheadTeacher
from k8s_llm_scheduler_tpu.types import (
    DecisionSource,
    NodeMetrics,
    PodSpec,
    SchedulingDecision,
)


class ArenaError(RuntimeError):
    pass


# ------------------------------------------------------------------- arms
class HeuristicBackend:
    """A core/fallback scorer served as a DecisionBackend, so the full
    client stack (cache, single-flight, breaker, validation) runs exactly
    as it would for the model — the arena measures the POLICY difference,
    not a plumbing difference. fallback_needed stays False: to the stack
    this IS the decider, not a degraded answer (and single-flight
    followers may reuse it, like any healthy leader decision)."""

    def __init__(self, strategy: str) -> None:
        if strategy not in SCORERS:
            raise ValueError(f"unknown strategy {strategy!r}")
        self.strategy = strategy

    def get_scheduling_decision(
        self, pod: PodSpec, nodes: Sequence[NodeMetrics]
    ) -> SchedulingDecision:
        from k8s_llm_scheduler_tpu.engine.backend import NoFeasibleNodeError

        decision = fallback_decision(
            nodes, reason="arena", strategy=self.strategy, pod=pod
        )
        if decision is None:
            raise NoFeasibleNodeError(
                f"no feasible node for pod {pod.namespace}/{pod.name}"
            )
        return dataclasses.replace(
            decision,
            fallback_needed=False,
            source=DecisionSource.LLM,
            confidence=0.5,
            reasoning=f"arena[{self.strategy}]",
        )


@dataclasses.dataclass
class ArmSpec:
    """One contender. `make()` returns a DecisionBackend (kind="stack") or
    a policy object with decide()/begin_wave() (kind="policy"). `owned`
    backends are closed by the arena after the run."""

    name: str
    kind: str                      # "stack" | "policy"
    make: Callable[[], Any]
    cache: bool = True
    owned: bool = True


def heuristic_arms() -> list[ArmSpec]:
    return [
        ArmSpec(name=s, kind="stack", make=lambda s=s: HeuristicBackend(s))
        for s in SCORERS
    ]


def teacher_arm() -> ArmSpec:
    return ArmSpec(
        name="teacher", kind="policy", make=SpreadLookaheadTeacher
    )


def stub_llm_arm() -> ArmSpec:
    """The zero-weights stand-in for the LLM arm: the full serving stack
    with engine/backend.StubBackend deciding — what `cli sim` runs when
    no model is configured."""
    from k8s_llm_scheduler_tpu.engine.backend import StubBackend

    return ArmSpec(name="stub-llm", kind="stack", make=StubBackend)


# ---------------------------------------------------------------- scoring
def score_placement(
    scenario: Scenario,
    placements: dict[str, str],
    unschedulable: Sequence[str] = (),
) -> dict:
    """Deterministic placement metrics for one arm's final state.

    Rebuilds the ClusterModel from the scenario and the placement map —
    the SAME computation trace replay performs, so a recorded trace's
    scores are reproducible from its decisions alone (bit-identity)."""
    pods_by_name = {p.name: p for wave in scenario.waves for p in wave}
    model = ClusterModel(scenario)
    for wave_idx in range(len(scenario.waves)):
        model.apply_churn(scenario.churn_for_wave(wave_idx))
    for pod_name in sorted(placements):
        model.place(pods_by_name[pod_name], placements[pod_name])

    final = model.metrics()
    fills = [n.pod_count / n.max_pods for n in final if n.max_pods]
    spread = statistics.pstdev(fills) if len(fills) > 1 else 0.0

    cpu_fracs = []
    mem_fracs = []
    node_facts = {n.name: n for n in scenario.nodes}
    for n in final:
        cpu_fracs.append(model.cpu_alloc[n.name] / n.available_cpu_cores
                         if n.available_cpu_cores else 0.0)
        mem_fracs.append(model.mem_alloc[n.name] / n.available_memory_gb
                         if n.available_memory_gb else 0.0)
    util_cpu = statistics.pstdev(cpu_fracs) if len(cpu_fracs) > 1 else 0.0
    util_mem = statistics.pstdev(mem_fracs) if len(mem_fracs) > 1 else 0.0

    # constraint satisfaction against STATIC node facts (labels, taints,
    # affinity); readiness-at-decision-time is the live stack's concern
    satisfied = 0
    for pod_name in sorted(placements):
        pod = pods_by_name[pod_name].to_pod_spec()
        fact = node_facts.get(placements[pod_name])
        if fact is None:
            continue
        node = NodeMetrics(
            name=fact.name, cpu_usage_percent=0.0, memory_usage_percent=0.0,
            available_cpu_cores=fact.cpu_cores,
            available_memory_gb=fact.memory_gb,
            pod_count=0, max_pods=fact.max_pods,
            labels=dict(fact.labels), taints=fact.taints,
            conditions={"Ready": "True"},
        )
        if (
            selector_matches(pod, node)
            and tolerates_taints(pod, node)
            and node_affinity_matches(pod, node)
        ):
            satisfied += 1

    # fragmentation vs the MEAN pod shape: stranded capacity that a pooled
    # cluster would still serve. Zero-pod scenarios have no shape to
    # fragment against — mean 0 routes every fit through the slot count.
    all_pods = list(pods_by_name.values())
    n_all = max(len(all_pods), 1)
    mean_cpu = sum(p.cpu_m for p in all_pods) / (1000.0 * n_all)
    mean_mem = sum(p.mem_mi for p in all_pods) / (1024.0 * n_all)
    fit = pooled_cpu = pooled_mem = pooled_slots = 0.0
    for n in final:
        cpu_free = max(n.available_cpu_cores - model.cpu_alloc[n.name], 0.0)
        mem_free = max(n.available_memory_gb - model.mem_alloc[n.name], 0.0)
        slots_free = max(n.max_pods - n.pod_count, 0)
        fit += min(
            int(cpu_free / mean_cpu) if mean_cpu else slots_free,
            int(mem_free / mean_mem) if mean_mem else slots_free,
            slots_free,
        )
        pooled_cpu += cpu_free
        pooled_mem += mem_free
        pooled_slots += slots_free
    pooled_fit = min(
        int(pooled_cpu / mean_cpu) if mean_cpu else pooled_slots,
        int(pooled_mem / mean_mem) if mean_mem else pooled_slots,
        pooled_slots,
    )
    fragmentation = 1.0 - (fit / pooled_fit) if pooled_fit else 0.0

    n_pods = scenario.n_pods
    return {
        "spread": round(spread, 6),
        "util_cpu_spread": round(util_cpu, 6),
        "util_mem_spread": round(util_mem, 6),
        "constraint_satisfaction": round(
            satisfied / len(placements), 6
        ) if placements else 1.0,
        "fragmentation": round(fragmentation, 6),
        "bound_frac": round(len(placements) / n_pods, 6) if n_pods else 1.0,
        "n_bound": len(placements),
        "n_unschedulable": len(unschedulable),
    }


# ------------------------------------------------------------ stack runner
async def _settle(predicate, timeout_s: float, what: str) -> None:
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            raise ArenaError(f"timed out settling: {what}")
        await asyncio.sleep(0.01)


async def _run_stack_arm(
    scenario: Scenario,
    backend: Any,
    *,
    use_cache: bool = True,
    max_concurrency: int = 64,
    wave_timeout_s: float = 300.0,
) -> tuple[dict[str, str], list[str], list[dict], dict]:
    """Run one backend arm end to end over the wire fake. Returns
    (placements, unschedulable, per-wave attribution, stats)."""
    from k8s_llm_scheduler_tpu.cluster.httpapi import (
        clear_active_config,
        set_active_config,
    )
    from k8s_llm_scheduler_tpu.cluster.kube import KubeCluster
    from k8s_llm_scheduler_tpu.cluster.wire_fake import WireFakeK8s
    from k8s_llm_scheduler_tpu.core.breaker import CircuitBreaker
    from k8s_llm_scheduler_tpu.core.cache import DecisionCache
    from k8s_llm_scheduler_tpu.sched.client import DecisionClient
    from k8s_llm_scheduler_tpu.sched.loop import Scheduler

    wire = WireFakeK8s(auto_run=True)
    cluster = None
    task = None
    try:
        apply_topology(scenario, wire)
        set_active_config(wire.base_url)
        cluster = KubeCluster(watch_timeout_seconds=10)
        client = DecisionClient(
            backend,
            cache=DecisionCache(max_size=4096) if use_cache else None,
            breaker=CircuitBreaker(),
            retry_delay=0.05,
        )
        scheduler = Scheduler(
            cluster, cluster, client,
            scheduler_name=SCHEDULER_NAME,
            snapshot_ttl_s=1e9,          # waves invalidate explicitly
            max_concurrency=max_concurrency,
            prefix_prewarm_s=0.0,        # determinism: no idle installs
        )

        # every bind converges on _note_bind: tag pod -> (node, source,
        # backend latency, bind wall time) without touching the loop
        outcomes: dict[str, tuple[str, str, float, float]] = {}
        orig_note = scheduler._note_bind

        def tagging_note(ok, pod, decision):
            if ok:
                outcomes[pod.name] = (
                    decision.selected_node,
                    decision.source.value,
                    decision.latency_ms,
                    time.perf_counter(),
                )
            orig_note(ok, pod, decision)

        scheduler._note_bind = tagging_note

        # Pods that resolved WITHOUT a bind (unschedulable, failed bind),
        # by name. A global-counter delta here would double-count when a
        # watch fresh-start re-delivers still-pending pods from earlier
        # waves (the 410 path wire_fake supports) and release the wave
        # barrier early — a set of names is idempotent under redelivery.
        unplaced: set[str] = set()
        orig_schedule = scheduler.schedule_pod

        async def tracking_schedule(raw, pod=None):
            ok = await orig_schedule(raw, pod)
            if not ok:
                unplaced.add(raw.name)
            return ok

        scheduler.schedule_pod = tracking_schedule
        task = asyncio.create_task(scheduler.run())

        model = ClusterModel(scenario)
        engine_stats = getattr(backend, "get_stats", None)
        placements: dict[str, str] = {}
        unschedulable: list[str] = []
        waves_out: list[dict] = []

        for wave_idx, wave in enumerate(scenario.waves):
            churn = scenario.churn_for_wave(wave_idx)
            if churn:
                apply_churn_to_wire(scenario, churn, wire)
                model.apply_churn(churn)
                expect = {
                    n.name: model.ready[n.name] for n in model.live_nodes()
                }

                def churn_settled() -> bool:
                    seen = {
                        n.name: n.is_ready
                        for n in cluster.get_node_metrics()
                    }
                    return seen == expect

                await _settle(
                    churn_settled, wave_timeout_s, f"churn@wave{wave_idx}"
                )
            if not wave:
                waves_out.append({"wave": wave_idx, "n_pods": 0})
                continue

            scheduler.invalidate_snapshot()
            phases_before = scheduler.phases.snapshot()
            engine_before = dict(engine_stats()) if engine_stats else {}
            t0 = time.perf_counter()
            for pod in wave:
                add_pod_to_wire(pod, wire)

            released = {p.name for p in wave}

            def wave_done() -> bool:
                return all(
                    n in outcomes or n in unplaced for n in released
                )

            await _settle(wave_done, wave_timeout_s, f"wave{wave_idx} drain")
            wall_s = time.perf_counter() - t0

            wave_bound = [n for n in released if n in outcomes]
            for name in wave_bound:
                placements[name] = outcomes[name][0]
            wave_unsched = sorted(released - set(wave_bound))
            unschedulable.extend(wave_unsched)
            for pod in wave:
                if pod.name in outcomes:
                    model.place(pod, outcomes[pod.name][0])

            # barrier: the informer must reflect every bind before the
            # next wave's snapshot (usage synthesis counts placements).
            # Count only pods on still-present nodes — a churn-deleted
            # node takes its placements out of the informer's view.
            total_bound = sum(
                1 for node in placements.values() if model.present.get(node)
            )

            def informer_settled(want=total_bound) -> bool:
                return sum(
                    n.pod_count for n in cluster.get_node_metrics()
                ) >= want

            await _settle(
                informer_settled, wave_timeout_s,
                f"wave{wave_idx} informer",
            )

            waves_out.append(
                _wave_attribution(
                    wave_idx, wave, outcomes, t0, wall_s,
                    phases_before, scheduler.phases.snapshot(),
                    engine_before,
                    dict(engine_stats()) if engine_stats else {},
                    wave_unsched,
                )
            )

        stats = scheduler.get_stats()
        return placements, unschedulable, waves_out, stats
    finally:
        if task is not None:
            scheduler.stop()
            cluster.close()
            try:
                await asyncio.wait_for(task, timeout=30)
            except asyncio.TimeoutError:
                task.cancel()
        elif cluster is not None:
            cluster.close()
        wire.close()
        # drop the process-global active config now pointing at a dead
        # server (same hygiene as the chaos harness): later clients must
        # fall back to real cluster discovery, not dial this address
        clear_active_config()


def _phase_delta(before: dict, after: dict, name: str) -> float:
    b = before.get(name, {}).get("total_ms", 0.0)
    a = after.get(name, {}).get("total_ms", 0.0)
    return a - b


def _wave_attribution(
    wave_idx: int,
    wave: list[SimPod],
    outcomes: dict,
    t0: float,
    wall_s: float,
    phases_before: dict,
    phases_after: dict,
    engine_before: dict,
    engine_after: dict,
    unschedulable: list[str],
) -> dict:
    """Decompose one wave's latency (the burst-residual instrument).

    Per-pod latency = bind wall time - wave release. Phase numbers are
    DELTAS of the scheduler's PhaseRecorder totals (sums over pods —
    concurrent phases legitimately exceed wall time). `admission_ms` is
    decide-total minus backend-total: time decisions spent queued in the
    client (semaphore, single-flight parking) rather than in the model.
    The prefill/decode split apportions backend time by the engine's
    token-count deltas — an estimate (flagged _est), absent for
    engine-less arms. `residual_p50_ms` is the per-pod median latency not
    covered by per-pod mean phase costs: the number that was previously
    invisible (~100 ms of unattributed burst latency, VERDICT r5)."""
    n = len(wave)
    lat = sorted(
        (outcomes[p.name][3] - t0) * 1000.0
        for p in wave if p.name in outcomes
    )
    backend_ms = sum(
        outcomes[p.name][2]
        for p in wave
        if p.name in outcomes and outcomes[p.name][1] == "llm"
    )
    n_llm = sum(
        1 for p in wave
        if p.name in outcomes and outcomes[p.name][1] == "llm"
    )
    snapshot_ms = _phase_delta(phases_before, phases_after, "snapshot")
    decide_ms = _phase_delta(phases_before, phases_after, "decide")
    bind_ms = _phase_delta(phases_before, phases_after, "bind")
    admission_ms = max(decide_ms - backend_ms, 0.0)
    # Window percentiles from HISTOGRAM bucket deltas (observability/trace):
    # this wave's own decide/bind p50/p95 — a per-wave total only says how
    # much time was spent, not how it was distributed over the wave's pods
    # (the avg hid exactly the tail the attribution exists to expose).
    from k8s_llm_scheduler_tpu.observability.trace import (
        delta_hist,
        hist_percentiles,
    )

    phase_pcts = {}
    for phase in ("decide", "bind"):
        dh = delta_hist(phases_before.get(phase), phases_after.get(phase))
        if dh and dh["count"]:
            p50, p95, _ = hist_percentiles(dh["counts"])
            phase_pcts[f"{phase}_p50_ms"] = round(p50, 3)
            phase_pcts[f"{phase}_p95_ms"] = round(p95, 3)
    out = {
        "wave": wave_idx,
        "n_pods": n,
        "n_bound": len(lat),
        "n_llm_leaders": n_llm,
        "n_unschedulable": len(unschedulable),
        "wall_ms": round(wall_s * 1000.0, 3),
        "pod_p50_ms": round(statistics.median(lat), 3) if lat else None,
        "pod_p95_ms": round(
            lat[min(len(lat) - 1, int(len(lat) * 0.95))], 3
        ) if lat else None,
        "snapshot_ms": round(snapshot_ms, 3),
        "decide_ms": round(decide_ms, 3),
        "bind_ms": round(bind_ms, 3),
        "backend_ms": round(backend_ms, 3),
        "admission_ms": round(admission_ms, 3),
        **phase_pcts,
    }
    pf = engine_after.get("prefill_tokens", 0) - engine_before.get(
        "prefill_tokens", 0
    )
    dc = engine_after.get("decode_tokens", 0) - engine_before.get(
        "decode_tokens", 0
    )
    if backend_ms and (pf + dc):
        out["prefill_ms_est"] = round(backend_ms * pf / (pf + dc), 3)
        out["decode_ms_est"] = round(backend_ms * dc / (pf + dc), 3)
        out["prefill_tokens"] = int(pf)
        out["decode_tokens"] = int(dc)
    if lat:
        per_pod_known = (snapshot_ms + decide_ms + bind_ms) / max(len(lat), 1)
        out["residual_p50_ms"] = round(
            max(statistics.median(lat) - per_pod_known, 0.0), 3
        )
    return out


# ----------------------------------------------------------- policy runner
def _run_policy_arm(
    scenario: Scenario, policy: Any
) -> tuple[dict[str, str], list[str], list[dict]]:
    """Sequential deterministic replay over the ClusterModel (stateful
    policies — the teacher). Wave structure and churn identical to the
    stack runner; 'latency' here is pure host compute."""
    model = ClusterModel(scenario)
    placements: dict[str, str] = {}
    unschedulable: list[str] = []
    waves_out: list[dict] = []
    if hasattr(policy, "reset"):
        policy.reset()
    for wave_idx, wave in enumerate(scenario.waves):
        model.apply_churn(scenario.churn_for_wave(wave_idx))
        if not wave:
            waves_out.append({"wave": wave_idx, "n_pods": 0})
            continue
        snapshot = model.metrics()
        if hasattr(policy, "begin_wave"):
            policy.begin_wave()
        t0 = time.perf_counter()
        decided: list[tuple[SimPod, str]] = []
        wave_unsched: list[str] = []
        for pod in wave:
            name = policy.decide(pod.to_pod_spec(), snapshot)
            if name is None:
                wave_unsched.append(pod.name)
            else:
                decided.append((pod, name))
        wall_s = time.perf_counter() - t0
        for pod, node in decided:
            model.place(pod, node)
            placements[pod.name] = node
        unschedulable.extend(wave_unsched)
        waves_out.append({
            "wave": wave_idx,
            "n_pods": len(wave),
            "n_bound": len(decided),
            "n_unschedulable": len(wave_unsched),
            "wall_ms": round(wall_s * 1000.0, 3),
            "decide_ms": round(wall_s * 1000.0, 3),
        })
    return placements, unschedulable, waves_out


# ------------------------------------------------------------------ arena
def run_arena(
    scenario: Scenario,
    arms: Sequence[ArmSpec],
    *,
    wave_timeout_s: float = 300.0,
    max_concurrency: int = 64,
    on_arm_done: "Callable[[str, dict], None] | None" = None,
) -> dict:
    """Run every arm over `scenario`; return the BENCH-style report.

    Report = {"scenario": ..., "arms": {name: {"scores", "waves",
    "stats"}}}. `scores`, each arm's `placements_digest`, and the per-arm
    placements (in the trace) are deterministic for a given scenario
    seed; `waves` carries the timing attribution and is expected to vary
    run to run. `on_arm_done(name, arm_report)` fires as each arm lands —
    the live hook `cli sim --metrics-port` exports scrapes through."""
    report_arms: dict[str, dict] = {}
    traces: dict[str, dict] = {}
    for arm in arms:
        impl = arm.make()
        try:
            if arm.kind == "stack":
                placements, unsched, waves, stats = asyncio.run(
                    _run_stack_arm(
                        scenario, impl,
                        use_cache=arm.cache,
                        max_concurrency=max_concurrency,
                        wave_timeout_s=wave_timeout_s,
                    )
                )
            elif arm.kind == "policy":
                placements, unsched, waves = _run_policy_arm(scenario, impl)
                stats = {}
            else:
                raise ValueError(f"unknown arm kind {arm.kind!r}")
        finally:
            if arm.owned and hasattr(impl, "close"):
                impl.close()
        scores = score_placement(scenario, placements, unsched)
        report_arms[arm.name] = {
            "kind": arm.kind,
            "scores": scores,
            # determinism witness without shipping the full map: two runs
            # of the same seed must print the same digest
            "placements_digest": placements_digest(placements),
            "waves": waves,
            "stats": _compact_stats(stats),
        }
        traces[arm.name] = {
            "placements": placements,
            "unschedulable": sorted(unsched),
            "scores": scores,
        }
        if on_arm_done is not None:
            on_arm_done(arm.name, report_arms[arm.name])
    return {
        "metric": "sim_arena",
        "scenario": scenario.spec.to_dict(),
        "arms": report_arms,
        "_traces": traces,  # consumed by sim/trace.py; stripped from JSON
    }


def placements_digest(placements: dict[str, str]) -> str:
    import hashlib

    # THE canonical serialization (sim/trace.py) — one definition of
    # byte-stable form, so the digest and the trace can never disagree
    from k8s_llm_scheduler_tpu.sim.trace import canonical_bytes

    return hashlib.sha256(canonical_bytes(placements)).hexdigest()[:16]


def _compact_stats(stats: dict) -> dict:
    """Keep the decision-mix counters; drop nested engine/client detail
    (the full stats surface via /metrics when a MetricsServer is up)."""
    keep = (
        "total_scheduled", "llm_decisions", "cache_decisions",
        "fallback_decisions", "failed_bindings", "unschedulable",
    )
    return {k: stats[k] for k in keep if k in stats}
