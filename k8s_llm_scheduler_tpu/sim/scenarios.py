"""Seeded scenario generators + the deterministic cluster twin.

SARATHI (arXiv:2308.16369) and Prepacking (arXiv:2404.09529) both show
that batching schedulers must be measured under realistic ARRIVAL
PROCESSES, not single-request microbenchmarks — yet through round 5 the
repo's only workload generator was `testing.pod_burst` (one shape ladder,
burst-at-t0, uniform nodes). This module generates the missing scenario
space, all from one seed:

- topologies: 3→256+ nodes across heterogeneous SKUs, zone/tier labels,
  NoSchedule taints;
- workloads: Poisson or burst arrivals quantized into WAVES (the unit the
  arena drains, scores, and attributes latency to), resource-shape mixes,
  and per-shape placement constraints drawn from the SAME scenario-class
  taxonomy `cli eval --scenarios` measures (train/eval.SCENARIO_CLASSES —
  arena scores and eval tables speak one language);
- churn: wave-indexed node failures/recoveries/additions/deletions
  (wall-clock-indexed churn would make replay nondeterministic).

`ClusterModel` is the deterministic twin of the informer's view (same
pod-count synthesized usage as cluster/kube.py and cluster/fake.py): the
scoring and trace-replay authority. The live run drives the REAL stack
over cluster/wire_fake.py; the model never decides, it only accounts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from k8s_llm_scheduler_tpu.cluster.interface import RawPod
from k8s_llm_scheduler_tpu.train.eval import SCENARIO_CLASSES, sample_pod_constraints
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec

SCHEDULER_NAME = "ai-llama-scheduler"

# (cpu cores, memory GB, max pods) — the SKU ladder topologies draw from;
# index 2 is the homogeneous default (testing.synthetic_cluster's shape).
SKUS = (
    (4.0, 16.0, 30),
    (8.0, 32.0, 60),
    (16.0, 64.0, 110),
    (64.0, 256.0, 250),
)


@dataclasses.dataclass(frozen=True)
class SimNode:
    name: str
    cpu_cores: float
    memory_gb: float
    max_pods: int
    labels: dict[str, str]
    taints: tuple[dict[str, str], ...] = ()
    ready: bool = True


@dataclasses.dataclass(frozen=True)
class SimPod:
    name: str
    shape: int               # shape id (cache-coherence group)
    kind: str                # scenario class (train/eval.SCENARIO_CLASSES)
    cpu_m: int               # CPU request, millicores
    mem_mi: int              # memory request, Mi
    node_selector: dict[str, str]
    tolerations: tuple[dict[str, Any], ...]
    affinity_terms: tuple[tuple[dict, ...], ...]  # normalized OR-of-ANDs
    arrival_s: float = 0.0
    priority: int = 0

    def to_pod_spec(self) -> PodSpec:
        """The normalized view core/validation + the teacher policy use —
        unit conversion matches cluster/interface.raw_pod_to_spec."""
        affinity = (
            {"node_affinity_terms": [list(t) for t in self.affinity_terms]}
            if self.affinity_terms
            else {}
        )
        return PodSpec(
            name=self.name,
            namespace="default",
            cpu_request=self.cpu_m / 1000.0,
            memory_request=self.mem_mi / 1024.0,
            node_selector=dict(self.node_selector),
            tolerations=self.tolerations,
            affinity_rules=affinity,
            priority=self.priority,
        )

    def to_raw_pod(self) -> RawPod:
        affinity = (
            {"node_affinity_terms": [list(t) for t in self.affinity_terms]}
            if self.affinity_terms
            else {}
        )
        return RawPod(
            name=self.name,
            namespace="default",
            scheduler_name=SCHEDULER_NAME,
            container_requests=(
                {"cpu": f"{self.cpu_m}m", "memory": f"{self.mem_mi}Mi"},
            ),
            node_selector=dict(self.node_selector),
            tolerations=self.tolerations,
            affinity=affinity,
            priority=self.priority,
        )


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """Applied (and settled) BEFORE wave `wave` is released."""

    wave: int
    kind: str        # fail | recover | add | delete
    node: str


@dataclasses.dataclass
class ScenarioSpec:
    """Everything a scenario is, in one seedable record."""

    name: str = "scenario"
    seed: int = 0
    n_nodes: int = 16
    n_pods: int = 64
    shapes: int = 8
    # burst | poisson | waves | multitenant | flap | diurnal
    arrival: str = "burst"
    arrival_rate: float = 500.0   # pods/sec (poisson / multitenant aggregate)
    wave_window_s: float = 0.1    # arrival quantization window (poisson)
    n_waves: int = 4              # explicit wave count (waves/flap/diurnal)
    # flap arrival (scale-thrash workloads): heavy and light waves
    # alternate; heavy waves carry this fraction of the pods — arrival
    # pressure flaps across the autoscaler's deadband every wave
    flap_heavy_frac: float = 0.85
    # diurnal arrival (the millions-of-users day curve, wave-quantized):
    # per-wave load follows 1 + amplitude*sin starting at the trough, so
    # one period ramps trough -> peak -> trough. amplitude 0.9 ~ a 19x
    # trough-to-peak swing (the ROADMAP "diurnal 10x" class).
    diurnal_amplitude: float = 0.9
    diurnal_period_waves: int = 0  # 0 = one full period over n_waves
    # multitenant arrival shaping: number of independent tenant sources.
    # Each tenant is an on/off Poisson stream whose share of the
    # aggregate rate is drawn lognormal (heavy-tailed) — a few heavy
    # tenants dominate and their on-periods overlap into the bursty,
    # long-tailed superposition a fleet frontend actually sees.
    tenants: int = 1
    hetero: bool = True           # draw node SKUs from the ladder
    zones: int = 4
    taint_frac: float = 0.0       # fraction of nodes carrying NoSchedule
    # per-shape constraint classes cycled over the shape ids; "uniform"
    # means unconstrained (the training distribution)
    constraint_mix: tuple[str, ...] = ("uniform",)
    churn: tuple[ChurnEvent, ...] = ()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["churn"] = [dataclasses.asdict(e) for e in self.churn]
        d["constraint_mix"] = list(self.constraint_mix)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        d = dict(d)
        d["churn"] = tuple(ChurnEvent(**e) for e in d.get("churn", ()))
        d["constraint_mix"] = tuple(d.get("constraint_mix") or ("uniform",))
        return cls(**d)


@dataclasses.dataclass
class Scenario:
    spec: ScenarioSpec
    nodes: list[SimNode]
    waves: list[list[SimPod]]    # pods grouped by release wave

    @property
    def n_pods(self) -> int:
        return sum(len(w) for w in self.waves)

    def churn_for_wave(self, wave: int) -> list[ChurnEvent]:
        return [e for e in self.spec.churn if e.wave == wave]

    def to_dict(self) -> dict:
        return {
            "spec": self.spec.to_dict(),
            "nodes": [dataclasses.asdict(n) for n in self.nodes],
            "waves": [
                [dataclasses.asdict(p) for p in wave] for wave in self.waves
            ],
        }


def _normalize_kinds(mix: Sequence[str]) -> tuple[str, ...]:
    for kind in mix:
        if kind not in SCENARIO_CLASSES:
            raise ValueError(
                f"unknown constraint class {kind!r} "
                f"(known: {SCENARIO_CLASSES})"
            )
    return tuple(mix) or ("uniform",)


def generate_scenario(spec: ScenarioSpec) -> Scenario:
    """One seed -> one fully-determined scenario (topology + workload).

    Every random draw comes from a single np.random.default_rng(seed)
    stream in a FIXED order, so the same spec always generates the same
    scenario object — the determinism the arena's acceptance bar
    (identical placements and scores across runs) is built on.
    """
    mix = _normalize_kinds(spec.constraint_mix)
    rng = np.random.default_rng(spec.seed)

    # ------------------------------------------------------------- topology
    nodes: list[SimNode] = []
    for i in range(spec.n_nodes):
        if spec.hetero:
            cpu, mem, max_pods = SKUS[int(rng.integers(len(SKUS)))]
        else:
            cpu, mem, max_pods = SKUS[2]
        labels = {
            "zone": f"z{i % max(1, spec.zones)}",
            "tier": "db" if i % 2 else "web",
        }
        taints: tuple[dict[str, str], ...] = ()
        if spec.taint_frac > 0 and rng.random() < spec.taint_frac:
            taints = (
                {"key": "dedicated", "value": "gpu", "effect": "NoSchedule"},
            )
        nodes.append(
            SimNode(
                name=f"sim-node-{i:03d}",
                cpu_cores=cpu,
                memory_gb=mem,
                max_pods=max_pods,
                labels=labels,
                taints=taints,
            )
        )

    # churn validated HERE, against the topology just generated: a typo'd
    # node name was previously a silent no-op in policy arms (phantom dict
    # key in ClusterModel) and a mid-run KeyError in stack arms — after
    # earlier arms had already burned their wall time
    known = {n.name for n in nodes}
    for e in spec.churn:
        if e.kind not in ("fail", "recover", "add", "delete"):
            raise ValueError(
                f"churn event {e}: unknown kind {e.kind!r} "
                f"(known: fail, recover, add, delete)"
            )
        if e.node not in known:
            raise ValueError(
                f"churn event {e}: node {e.node!r} is not in this "
                f"topology (nodes are sim-node-000..{spec.n_nodes - 1:03d})"
            )

    # ---------------------------------------------------- per-shape draws
    # Constraints are drawn ONCE per shape and shared by every pod of that
    # shape — replicas of one deployment carry one pod template, and this
    # is exactly what makes the decision cache's single-flight economics
    # realistic (8 shapes -> ~8 leaders per wave, not n_pods).
    shape_constraints: list[tuple[dict, tuple, dict]] = []
    shape_kinds: list[str] = []
    for s in range(spec.shapes):
        kind = mix[s % len(mix)]
        shape_kinds.append(kind)
        shape_constraints.append(sample_pod_constraints(kind, rng))

    # ------------------------------------------------------------ arrivals
    if spec.arrival == "poisson":
        gaps = rng.exponential(1.0 / max(spec.arrival_rate, 1e-9), spec.n_pods)
        arrivals = np.cumsum(gaps)
        wave_of = (arrivals // max(spec.wave_window_s, 1e-9)).astype(int)
        # compact to consecutive wave ids (empty windows carry no info)
        _, wave_of = np.unique(wave_of, return_inverse=True)
    elif spec.arrival == "waves":
        n_waves = max(1, spec.n_waves)
        arrivals = np.zeros(spec.n_pods)
        wave_of = np.minimum(
            np.arange(spec.n_pods) * n_waves // max(1, spec.n_pods),
            n_waves - 1,
        )
    elif spec.arrival == "multitenant":
        # Superposition of per-tenant on/off Poisson sources. Tenant
        # shares are lognormal (heavy-tailed: a handful of tenants carry
        # most of the traffic — the millions-of-users shape, where "user
        # demand" reaches the scheduler as deployments scaling replicas);
        # each tenant's stream starts at its own offset so bursts overlap
        # instead of aligning at t0.
        tenants = max(1, spec.tenants)
        weights = rng.lognormal(mean=0.0, sigma=1.5, size=tenants)
        weights = weights / weights.sum()
        counts = rng.multinomial(spec.n_pods, weights)
        horizon = spec.n_pods / max(spec.arrival_rate, 1e-9)
        streams = []
        for t in range(tenants):
            if counts[t] == 0:
                continue
            # tenant rate ~ its share of the aggregate; the on-period
            # offset spreads tenants over the first half of the horizon
            rate = max(spec.arrival_rate * float(weights[t]), 1e-9)
            start = float(rng.uniform(0.0, horizon * 0.5))
            gaps = rng.exponential(1.0 / rate, int(counts[t]))
            streams.append(start + np.cumsum(gaps))
        arrivals = np.sort(np.concatenate(streams)) if streams else np.zeros(0)
        wave_of = (arrivals // max(spec.wave_window_s, 1e-9)).astype(int)
        _, wave_of = np.unique(wave_of, return_inverse=True)
    elif spec.arrival == "flap":
        # alternating heavy/light waves: the scale-thrash workload.
        # Allocation is pure arithmetic (no rng draw) so the flap shape
        # is identical across seeds that share a geometry.
        n_waves = max(2, spec.n_waves)
        heavy = [w for w in range(n_waves) if w % 2 == 0]
        light = [w for w in range(n_waves) if w % 2 == 1]
        n_heavy = int(round(spec.n_pods * min(max(spec.flap_heavy_frac, 0.0), 1.0)))
        counts = np.zeros(n_waves, dtype=int)
        for group, total in ((heavy, n_heavy), (light, spec.n_pods - n_heavy)):
            base, rem = divmod(total, len(group))
            for j, w in enumerate(group):
                counts[w] = base + (1 if j < rem else 0)
        wave_of = np.repeat(np.arange(n_waves), counts)
        arrivals = np.zeros(spec.n_pods)
    elif spec.arrival == "diurnal":
        # wave-quantized day curve: per-wave weight 1 + A*sin starting
        # at the trough (wave 0 lightest, peak mid-period). Pod counts
        # come from largest-remainder apportionment of the weights —
        # deterministic, and the total is exactly n_pods.
        n_waves = max(1, spec.n_waves)
        period = spec.diurnal_period_waves or n_waves
        phase = 2.0 * np.pi * (np.arange(n_waves) + 0.5) / max(period, 1)
        weights = 1.0 + spec.diurnal_amplitude * np.sin(phase - np.pi / 2.0)
        weights = np.clip(weights, 0.0, None)
        if weights.sum() <= 0:
            weights = np.ones(n_waves)
        weights = weights / weights.sum()
        raw = weights * spec.n_pods
        counts = np.floor(raw).astype(int)
        remainder = spec.n_pods - int(counts.sum())
        order = np.argsort(-(raw - counts), kind="stable")
        counts[order[:remainder]] += 1
        wave_of = np.repeat(np.arange(n_waves), counts)
        arrivals = np.zeros(spec.n_pods)
    elif spec.arrival == "burst":
        arrivals = np.zeros(spec.n_pods)
        wave_of = np.zeros(spec.n_pods, dtype=int)
    else:
        raise ValueError(f"unknown arrival process {spec.arrival!r}")

    # ---------------------------------------------------------------- pods
    n_churn_waves = max((e.wave for e in spec.churn), default=-1) + 1
    n_waves_total = max(int(wave_of.max()) + 1 if spec.n_pods else 1,
                        n_churn_waves)
    if spec.arrival in ("flap", "diurnal"):
        # a trough wave may carry ZERO pods — it still exists (the
        # harness's fault windows and the autoscaler's down-scale ticks
        # are indexed by wave, and an idle wave is exactly when a
        # scale-down should fire)
        n_waves_total = max(n_waves_total, max(1, spec.n_waves))
    waves: list[list[SimPod]] = [[] for _ in range(n_waves_total)]
    for i in range(spec.n_pods):
        shape = i % spec.shapes
        selector, tolerations, affinity = shape_constraints[shape]
        terms = tuple(
            tuple(term) for term in affinity.get("node_affinity_terms", [])
        )
        waves[int(wave_of[i])].append(
            SimPod(
                name=f"sim-pod-{i:04d}",
                shape=shape,
                kind=shape_kinds[shape],
                cpu_m=100 + 50 * shape,
                mem_mi=128 * (1 + shape % 4),
                node_selector=selector,
                tolerations=tolerations,
                affinity_terms=terms,
                arrival_s=round(float(arrivals[i]), 6),
                priority=shape % 3,
            )
        )
    return Scenario(spec=spec, nodes=nodes, waves=waves)


# ---------------------------------------------------------- fleet scenarios
# Named fleet-scale scenario classes (ROADMAP open item 4): arrival
# traces shaped like heavy multi-tenant traffic against large hetero
# topologies. `fleet-500` is the fast-tier variant (CI, bench.py
# --preset fleet); `fleet-10k` is the 10k-node / 100k-pod class (slow
# tier — generation is seconds, driving it through a live stack is a
# deliberate soak). Specs are returned by value: callers may mutate
# their copy (seed sweeps, pod-count overrides) without corrupting the
# registry.
FLEET_SCENARIOS: dict[str, ScenarioSpec] = {
    "fleet-500": ScenarioSpec(
        name="fleet-500",
        seed=7,
        n_nodes=500,
        n_pods=5_000,
        shapes=64,
        arrival="multitenant",
        tenants=24,
        arrival_rate=5_000.0,
        wave_window_s=0.05,
        hetero=True,
        zones=8,
        taint_frac=0.02,
        constraint_mix=("uniform", "selector", "uniform", "tainted"),
    ),
    "fleet-10k": ScenarioSpec(
        name="fleet-10k",
        seed=7,
        n_nodes=10_000,
        n_pods=100_000,
        shapes=512,
        arrival="multitenant",
        tenants=200,
        arrival_rate=50_000.0,
        wave_window_s=0.05,
        hetero=True,
        zones=16,
        taint_frac=0.02,
        constraint_mix=("uniform", "selector", "uniform", "tainted"),
    ),
}


def fleet_scenario(name: str) -> ScenarioSpec:
    """A copy of a named fleet scenario spec (see FLEET_SCENARIOS)."""
    try:
        spec = FLEET_SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown fleet scenario {name!r} "
            f"(known: {sorted(FLEET_SCENARIOS)})"
        ) from None
    return dataclasses.replace(spec)


# ---------------------------------------------------------- chaos scenarios
def chaos_scenario(
    regime: str,
    seed: int = 0,
    *,
    n_nodes: int = 12,
    n_pods: int = 96,
    shapes: int = 8,
    n_waves: int = 8,
):
    """(ScenarioSpec, FaultPlan) for one chaos regime (chaos/faults.py
    REGIMES): the workload side is a wave-quantized scenario from THIS
    module's generator — same seed discipline, same churn machinery (the
    node-failure and autoscaler regimes ride ChurnEvent exactly like
    arena scenarios) — and the fault side is the regime's seeded
    FaultPlan over the same virtual (wave) clock. One seed determines
    both, which is what makes a chaos run a replayable artifact.

    Constraints stay uniform on purpose: every pod must be placeable so
    the invariant monitor's lost-pod accounting is exact (an
    unschedulable-by-construction pod would be indistinguishable from a
    dropped one without carrying the constraint solver into the chaos
    verdict)."""
    from k8s_llm_scheduler_tpu.chaos.faults import REGIMES, FaultPlan

    plan = FaultPlan.generate(regime, seed, n_waves, n_nodes=n_nodes)
    churn = tuple(
        ChurnEvent(wave=int(c["wave"]), kind=c["kind"], node=c["node"])
        for c in plan.churn
    )
    # scale regimes shape the workload side too: flap parks arrival
    # pressure on the autoscaler's threshold, diurnal ramps it through
    # the fault window (chaos/faults.REGIMES declares which)
    arrival = REGIMES[regime].get("arrival", "waves")
    spec = ScenarioSpec(
        name=f"chaos-{regime}",
        seed=seed,
        n_nodes=n_nodes,
        n_pods=n_pods,
        shapes=shapes,
        arrival=arrival,
        n_waves=n_waves,
        hetero=True,
        zones=4,
        taint_frac=0.0,
        constraint_mix=("uniform",),
        churn=churn,
    )
    return spec, plan


# --------------------------------------------------------------- twin model
class ClusterModel:
    """Deterministic in-memory twin of what the informer would report.

    Usage synthesis parity: (pod_count / max_pods) * 50 — the exact
    stand-in cluster/kube.py and cluster/fake.py use when metrics-server
    is absent, so a policy decided against the model sees the same
    numbers a policy decided against the live stack sees. Also tracks
    requested-resource allocation per node (the live NodeMetrics carries
    CAPACITY, not allocation — utilization-balance scoring needs the
    latter)."""

    def __init__(self, scenario: Scenario) -> None:
        self._base = {n.name: n for n in scenario.nodes}
        self.ready: dict[str, bool] = {n.name: n.ready for n in scenario.nodes}
        self.present: dict[str, bool] = {n.name: True for n in scenario.nodes}
        self.pod_count: dict[str, int] = {n.name: 0 for n in scenario.nodes}
        self.cpu_alloc: dict[str, float] = {n.name: 0.0 for n in scenario.nodes}
        self.mem_alloc: dict[str, float] = {n.name: 0.0 for n in scenario.nodes}

    def apply_churn(self, events: Sequence[ChurnEvent]) -> None:
        for e in events:
            if e.kind == "fail":
                self.ready[e.node] = False
            elif e.kind == "recover":
                self.ready[e.node] = True
            elif e.kind == "delete":
                self.present[e.node] = False
            elif e.kind == "add":
                self.present[e.node] = True
                # parity with apply_churn_to_wire, which re-adds the node
                # ready=True: a fail->delete->add sequence must converge to
                # the same state on both sides or the stack runner's churn
                # barrier never settles
                self.ready[e.node] = True
            else:
                raise ValueError(f"unknown churn kind {e.kind!r}")

    def place(self, pod: SimPod, node: str) -> None:
        self.pod_count[node] += 1
        self.cpu_alloc[node] += pod.cpu_m / 1000.0
        self.mem_alloc[node] += pod.mem_mi / 1024.0

    def live_nodes(self) -> list[SimNode]:
        return [n for name, n in self._base.items() if self.present[name]]

    def metrics(self) -> list[NodeMetrics]:
        """The snapshot a decision would see (informer synthesis parity)."""
        out = []
        for name, node in self._base.items():
            if not self.present[name]:
                continue
            count = self.pod_count[name]
            synth = (count / node.max_pods) * 50.0 if node.max_pods else 0.0
            out.append(
                NodeMetrics(
                    name=name,
                    cpu_usage_percent=synth,
                    memory_usage_percent=synth,
                    available_cpu_cores=node.cpu_cores,
                    available_memory_gb=node.memory_gb,
                    pod_count=count,
                    max_pods=node.max_pods,
                    labels=dict(node.labels),
                    taints=node.taints,
                    conditions={
                        "Ready": "True" if self.ready[name] else "False"
                    },
                )
            )
        return out


# ------------------------------------------------------------ wire plumbing
def apply_topology(scenario: Scenario, wire) -> None:
    """Install the scenario's nodes into a WireFakeK8s — quantity strings
    exactly as an API server would serve them."""
    for n in scenario.nodes:
        wire.add_node(
            n.name,
            cpu=_cpu_str(n.cpu_cores),
            memory=f"{int(n.memory_gb * 1024)}Mi",
            pods=str(n.max_pods),
            labels=n.labels,
            taints=list(n.taints),
            ready=n.ready,
        )


def add_pod_to_wire(pod: SimPod, wire) -> None:
    from k8s_llm_scheduler_tpu.cluster.wire_fake import node_affinity_wire

    affinity = (
        node_affinity_wire([list(t) for t in pod.affinity_terms])
        if pod.affinity_terms
        else None
    )
    wire.add_pod(
        pod.name,
        scheduler_name=SCHEDULER_NAME,
        requests={"cpu": f"{pod.cpu_m}m", "memory": f"{pod.mem_mi}Mi"},
        node_selector=pod.node_selector,
        tolerations=list(pod.tolerations),
        affinity=affinity,
        priority=pod.priority,
    )


def apply_churn_to_wire(scenario: Scenario, events: Sequence[ChurnEvent],
                        wire) -> None:
    by_name = {n.name: n for n in scenario.nodes}
    for e in events:
        if e.kind == "fail":
            wire.set_node_ready(e.node, False)
        elif e.kind == "recover":
            wire.set_node_ready(e.node, True)
        elif e.kind == "delete":
            wire.delete_node(e.node)
        elif e.kind == "add":
            n = by_name[e.node]
            wire.add_node(
                n.name, cpu=_cpu_str(n.cpu_cores),
                memory=f"{int(n.memory_gb * 1024)}Mi",
                pods=str(n.max_pods), labels=n.labels,
                taints=list(n.taints), ready=True,
            )
        else:
            raise ValueError(f"unknown churn kind {e.kind!r}")


def _cpu_str(cores: float) -> str:
    return str(int(cores)) if float(cores).is_integer() else f"{int(cores * 1000)}m"
