"""Spread-lookahead + soft-affinity reference policy.

The runtime fallback (core/fallback.py) is deliberately a stateless
one-shot ranking: O(nodes) per decision, no memory, because it runs on
the hot path when the model is down. This teacher is the policy the
runtime CANNOT afford — the reference arm the arena scores every other
arm against:

- **one-step spread lookahead**: for each feasible candidate, project the
  placement and score the RESULTING cluster's pod-fill spread (pstdev of
  fractional fills, the same metric train/eval.load_spread reports), then
  pick the future with the least imbalance. The greedy scorers rank the
  present; this ranks the consequence.
- **soft zone anti-affinity**: pods of one shape group (replicas of one
  deployment) are nudged across zones — a per-(group, zone) count the
  policy folds itself, since no NodeMetrics carries it. Soft: it breaks
  ties and biases, never vetoes a feasible node.
- feasibility first: candidates come from core/validation.feasible_nodes,
  identical to what the constrained decoder enforces for the LLM arm.

Stateful ⇒ order-dependent ⇒ the arena runs this arm in SEQUENTIAL
policy mode (one decision at a time over the deterministic ClusterModel),
not through the concurrent stack. That is what "reference" means here:
the score an oracle-ish planner reaches, for the live arms to chase.
"""

from __future__ import annotations

import math
from collections import Counter
from collections.abc import Sequence

from k8s_llm_scheduler_tpu.core.fallback import score_resource_balanced
from k8s_llm_scheduler_tpu.core.validation import feasible_nodes
from k8s_llm_scheduler_tpu.types import NodeMetrics, PodSpec



def pod_group(pod: PodSpec) -> str:
    """Shape signature = deployment stand-in (matches the decision-cache
    notion that same-shape pods are replicas of one workload)."""
    return f"{pod.cpu_request:.3f}|{pod.memory_request:.3f}"


class SpreadLookaheadTeacher:
    """Callable policy: decide(pod, nodes) -> node name | None.

    Within a wave the teacher also projects its OWN in-flight placements
    (`begin_wave` resets the projection once the cluster state has folded
    the binds in), so 30 identical replicas in one wave fan out instead
    of stacking on the one currently-best node — the exact failure mode
    of the cached fallback arm it exists to contrast."""

    def __init__(self) -> None:
        self._zone_counts: dict[str, Counter] = {}   # group -> zone -> n
        self._wave_counts: Counter = Counter()       # node -> in-wave adds

    def reset(self) -> None:
        self._zone_counts.clear()
        self._wave_counts.clear()

    def begin_wave(self) -> None:
        """The driver settled all previous binds into the snapshot: the
        per-node projection is now double-counting and must drop. The
        per-(group, zone) memory persists — no snapshot carries it."""
        self._wave_counts.clear()

    def decide(self, pod: PodSpec, nodes: Sequence[NodeMetrics]) -> str | None:
        candidates = feasible_nodes(pod, nodes)
        # project in-wave placements into the candidate filter too: a node
        # at max_pods - 1 with one in-wave add is FULL for this pod
        candidates = [
            n for n in candidates
            if n.pod_count + self._wave_counts[n.name] < n.max_pods
        ]
        if not candidates:
            return None
        group = pod_group(pod)
        zones = self._zone_counts.setdefault(group, Counter())

        fills = {
            n.name: (
                (n.pod_count + self._wave_counts[n.name]) / n.max_pods
                if n.max_pods
                else 0.0
            )
            for n in nodes
        }
        # incremental variance: only ONE fill changes per candidate, so
        # the projected pstdev is O(1) from the running sum / sum-of-
        # squares — the naive per-candidate recompute made a 256-node /
        # 1000-pod scenario O(nodes^2 * pods) and minutes-slow
        count = len(fills)
        f_sum = sum(fills.values())
        f_sumsq = sum(v * v for v in fills.values())

        def cost(n: NodeMetrics) -> tuple:
            old = fills[n.name]
            new = (
                (n.pod_count + self._wave_counts[n.name] + 1) / n.max_pods
                if n.max_pods
                else old
            )
            s = f_sum - old + new
            sq = f_sumsq - old * old + new * new
            var = max(sq / count - (s / count) ** 2, 0.0)
            spread_after = math.sqrt(var) if count > 1 else 0.0
            zone_pressure = zones.get(n.labels.get("zone", ""), 0)
            # LEXICOGRAPHIC, not weighted: the lookahead spread is the
            # headline objective and must never be outbid by a soft term
            # (a weighted blend measurably placed WORSE than the greedy
            # heuristics it exists to beat); zone anti-affinity breaks
            # spread ties, the balanced-resource score breaks the rest,
            # the name makes the order total (determinism).
            return (
                round(spread_after, 9),
                zone_pressure,
                -score_resource_balanced(n),
                n.name,
            )

        best = min(candidates, key=cost)
        self._wave_counts[best.name] += 1
        zones[best.labels.get("zone", "")] += 1
        return best.name
