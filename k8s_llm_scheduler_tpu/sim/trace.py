"""Deterministic trace record/replay for arena runs.

A trace is the DETERMINISTIC payload of an arena run: the scenario spec
(the seed regenerates topology and workload bit-for-bit), every arm's
placement map, its unschedulable set, and its scores. Timing — wave
attribution, wall clocks — deliberately lives OUTSIDE the trace, in the
report: it varies run to run and would break bit-identity.

Replay re-derives everything derivable:
1. regenerate the scenario from the recorded spec,
2. re-fold each arm's recorded placements through the ClusterModel,
3. recompute scores with arena.score_placement,
4. re-serialize canonically.

`verify_trace` asserts the recomputed bytes equal the recorded bytes —
the acceptance bar "replaying a recorded trace is bit-identical". Any
drift (a scoring change, a scenario-generator change, a corrupted file)
surfaces as a byte diff, never silently.

Canonical form: JSON with sorted keys, no whitespace, UTF-8. All floats
inside are round()ed at fixed precision by their producers, so equal
values serialize to equal bytes.
"""

from __future__ import annotations

import json
from pathlib import Path

from k8s_llm_scheduler_tpu.sim.scenarios import ScenarioSpec, generate_scenario

TRACE_VERSION = 1


def canonical_bytes(obj: dict) -> bytes:
    return json.dumps(
        obj, sort_keys=True, separators=(",", ":"), ensure_ascii=True
    ).encode("utf-8")


def build_trace(report: dict) -> dict:
    """Extract the deterministic trace from an arena report (run_arena
    attaches per-arm placements under the private "_traces" key)."""
    return {
        "version": TRACE_VERSION,
        "scenario_spec": report["scenario"],
        "arms": report["_traces"],
    }


def save_trace(report: dict, path: str | Path) -> bytes:
    data = canonical_bytes(build_trace(report))
    Path(path).write_bytes(data)
    return data


def load_trace(path: str | Path) -> dict:
    return json.loads(Path(path).read_bytes().decode("utf-8"))


def replay_trace(trace: dict) -> dict:
    """Recompute the trace from its own spec + decisions. Returns a NEW
    trace dict whose canonical bytes must equal the original's."""
    from k8s_llm_scheduler_tpu.sim.arena import score_placement

    if trace.get("version") != TRACE_VERSION:
        raise ValueError(
            f"trace version {trace.get('version')!r} != {TRACE_VERSION}"
        )
    spec = ScenarioSpec.from_dict(trace["scenario_spec"])
    scenario = generate_scenario(spec)
    pod_names = {p.name for wave in scenario.waves for p in wave}
    arms_out: dict[str, dict] = {}
    for arm, rec in trace["arms"].items():
        placements = dict(rec["placements"])
        unknown = set(placements) - pod_names
        if unknown:
            raise ValueError(
                f"arm {arm!r}: trace places pods the scenario never "
                f"generated: {sorted(unknown)[:5]}"
            )
        scores = score_placement(
            scenario, placements, rec.get("unschedulable", ())
        )
        arms_out[arm] = {
            "placements": placements,
            "unschedulable": sorted(rec.get("unschedulable", ())),
            "scores": scores,
        }
    return {
        "version": TRACE_VERSION,
        "scenario_spec": spec.to_dict(),
        "arms": arms_out,
    }


def verify_trace(path: str | Path) -> tuple[bool, str]:
    """(ok, detail): replay the recorded trace and byte-compare."""
    recorded = Path(path).read_bytes()
    replayed = canonical_bytes(replay_trace(json.loads(recorded)))
    # normalize the recorded side through canonical serialization too, so
    # a hand-pretty-printed (but semantically identical) file still passes
    recorded_canon = canonical_bytes(json.loads(recorded))
    if replayed == recorded_canon:
        return True, f"bit-identical ({len(replayed)} bytes)"
    import difflib

    a = json.dumps(json.loads(recorded_canon), indent=1, sort_keys=True)
    b = json.dumps(json.loads(replayed), indent=1, sort_keys=True)
    diff = "\n".join(
        list(difflib.unified_diff(a.splitlines(), b.splitlines(),
                                  "recorded", "replayed"))[:40]
    )
    return False, f"replay diverged:\n{diff}"
