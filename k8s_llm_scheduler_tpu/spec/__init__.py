"""Speculative decoding subsystem (SwiftSpec-shaped; PAPERS.md 2506.11309).

A small distilled DRAFT model (train/distill.py produces exactly this) runs
K tokens ahead of the big TARGET on the engine's general paged-decode path;
the target scores all K proposals in ONE forward and accepts the longest
target-consistent prefix (greedy) or rejection-samples so the emitted
distribution is exactly the target's (sampling). Rejected draft tokens
unwind through the paged-KV rollback op (engine/kv_cache.py truncate).
Grammar composition is built in: proposals and verification both sample
through the engine's SparseDFATables, so speculation can never emit a token
the constrained decoder would forbid.

Modules:
- draft.py   — DraftRunner: dense-KV draft state + the fused K-step
               propose program (one dispatch proposes all K tokens).
- verify.py  — the one-forward target scoring program over the paged cache
               plus on-device accept logic (greedy longest-prefix /
               distribution-preserving rejection sampling).
- decoder.py — SpeculativeDecoder: orchestration, per-request acceptance
               EWMA with auto-disable, fallback to plain chunked decode,
               metrics/trace export.
"""

from k8s_llm_scheduler_tpu.spec.decoder import SpeculativeDecoder
from k8s_llm_scheduler_tpu.spec.draft import DraftRunner

__all__ = ["SpeculativeDecoder", "DraftRunner"]
