"""Speculative decoding: asynchronous draft-ahead / verify-behind pipeline.

(*SwiftSpec* + *Hidden Transfer*, PAPERS.md.) A DRAFT arm (a small
distilled model — train/distill.py produces exactly this) or a draft-free
HIDDEN-TRANSFER arm (transfer heads over the target's own hidden states —
train/hidden.py) proposes K tokens ahead of the big TARGET on the
engine's general paged-decode path; the target scores all K proposals in
ONE forward with on-device acceptance (greedy longest-consistent-prefix,
or rejection sampling that preserves the target distribution exactly).

The pipeline is ASYNCHRONOUS: each round enqueues target-verify and the
draft's ahead-proposal for the NEXT block back-to-back and syncs once —
on a matched guess the next round's block is already device-resident, so
the draft runs in the shadow of the verify (the hidden arm goes further:
its proposals are computed INSIDE the verify program). Speculative
streams COEXIST with the fused decode runtime — an open round deactivates
only its own slot, never the engine (`fused_hold` is gone). Rejected
tokens unwind through the paged-KV rollback op (engine/kv_cache.truncate);
grammar composition is built in (sparse K-space tables, or the fused
runtime's dense transition table for greedy verification), so speculation
can never emit a token the constrained decoder would forbid.

Modules:
- draft.py   — DraftRunner: dense-KV draft state + the fused K+1-step
               propose program (one dispatch proposes the block AND the
               bonus-token guess the ahead pipeline anchors on).
- verify.py  — the one-forward target scoring program over the paged cache
               plus on-device accept logic, shared by both arms.
- hidden.py  — the draft-free arm's fused verify+propose program
               (transfer-head proposal chain grown on device).
- decoder.py — SpeculativeDecoder: the round state machine, per-request
               acceptance EWMA with auto-disable onto the FUSED decode
               path, swap rollback hook, SPEC_SEGMENTS profiler fencing.
"""

from k8s_llm_scheduler_tpu.spec.decoder import SpeculativeDecoder
from k8s_llm_scheduler_tpu.spec.draft import DraftRunner

__all__ = ["SpeculativeDecoder", "DraftRunner"]
