"""SpeculativeDecoder: asynchronous draft-ahead / verify-behind pipeline.

The serial shape this module replaced ran propose -> verify -> blocking
fetch per round, and held the whole fused runtime off while a request was
open (`engine.fused_hold`). The rebuilt decoder is a ROUND STATE MACHINE
over the engine's general paged-decode state that composes with — instead
of excluding — the fused runtime:

- **Pipelined dispatch, one sync per round** (*SwiftSpec*, PAPERS.md).
  Each round enqueues target-verify for block K and the draft's AHEAD
  proposal for block K+1 back-to-back, then fetches once. The ahead
  proposal anchors on the draft's own guess at the round's bonus token
  (spec/draft.py returns the K+1-th sample instead of discarding it), so
  when the verify fully accepts and the bonus matches the guess — every
  steady-state round for a well-matched draft, ALWAYS for a greedy
  self-draft — the next round's block is already device-resident and the
  draft ran entirely in the shadow of the verify sync. A miss discards
  the ahead block (the dense draft buffer re-proposes from the corrected
  token; stale entries are never attended — position-masked) and costs
  exactly the old serial round.
- **Fused-runtime coexistence**. A speculative request's slot is marked
  `external` and deactivated in the engine's decode batch at start():
  fused chunks for OTHER slots dispatch freely between (and during)
  spec rounds — everything rides one device queue in dispatch order —
  and `engine.fused_hold` is GONE. The auto-disable hand-off re-arms the
  slot and finishes through `engine.step_fused`, so a disabled request
  rides the fused runtime instead of the slow chunked path.
- **Dense-table grammar** (engine/fused/tables.py). Greedy constrained
  verification masks and transitions through the SAME dense
  transition-table the fused while_loop gathers from; sampling mode and
  cap-exceeded grammars keep the sparse K-space tables (spec/verify.py).
- **Draft-free hidden-transfer arm** (*Hidden Transfer*, PAPERS.md;
  spec/hidden.py). `arm="hidden"` drops the draft model: proposals come
  from transfer heads applied to the target's own hidden state INSIDE
  the verify program, so each round is ONE dispatch + one fetch and the
  proposal block rides device-resident between rounds.

Robustness is unchanged in kind, upgraded in destination: the per-request
acceptance EWMA still auto-disables a draft that stops earning its keep,
but the mid-stream hand-off now lands on the fused decode path; the
grammar-safe `PagedKVCache.truncate` rollback still absorbs every
mis-speculated tail; and `on_swap` (called by engine.swap_params) rolls
back any open speculative block before new weights install. Per-request
round telemetry fences into the profiler's SPEC_SEGMENTS books
(observability/profiler.py: draft/verify/rollback/unattributed, sum ==
wall) with the measured draft/verify overlap fraction beside them.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import Params, init_hidden_transfer
from k8s_llm_scheduler_tpu.observability.trace import recorder
from k8s_llm_scheduler_tpu.spec.draft import DraftRunner
from k8s_llm_scheduler_tpu.spec.verify import _verify_impl


@dataclasses.dataclass
class SpecStats:
    requests: int = 0
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0
    emitted: int = 0
    disables: int = 0
    fallback_requests: int = 0
    unsupported_requests: int = 0
    # Async-pipeline books: rounds whose proposal block was already
    # device-resident when the round began (the draft ran in the shadow
    # of the previous verify), ahead proposals discarded on a miss, and
    # open-block rollbacks forced by a weight swap.
    overlapped_rounds: int = 0
    ahead_wasted: int = 0
    swap_rollbacks: int = 0

    def snapshot(self) -> dict[str, Any]:
        out = dataclasses.asdict(self)
        out["acceptance_rate"] = (
            self.accepted / self.proposed if self.proposed else 0.0
        )
        out["tokens_per_round"] = (
            self.emitted / self.rounds if self.rounds else 0.0
        )
        out["overlap_fraction"] = (
            self.overlapped_rounds / self.rounds if self.rounds else 0.0
        )
        return out


@dataclasses.dataclass
class _Proposal:
    """A draft proposal block, fully device-resident (draft arm).

    `anchor_tok`/`anchor_st` are the token the block continues from and
    the DFA state after it (device scalars — an ahead proposal's anchor
    is the previous block's guess, never fetched). `toks`/`states` are
    the [K+1] proposal chain (index K = the draft's guess at the round's
    bonus token); `idxs`/`logits` feed the rejection sampler."""

    anchor_tok: jax.Array
    anchor_st: jax.Array
    pos: int  # anchor's absolute position (host bookkeeping)
    toks: jax.Array
    states: jax.Array
    idxs: jax.Array
    logits: jax.Array


@dataclasses.dataclass
class _HiddenBlock:
    """The hidden arm's next proposal block: produced inside the previous
    round's verify program, host copies fetched in that round's single
    sync (the emit path needs token values without a second fetch)."""

    pos: int  # anchor's absolute position
    toks: jax.Array
    states: jax.Array
    idxs: jax.Array
    logits: jax.Array
    toks_np: np.ndarray
    states_np: np.ndarray


@dataclasses.dataclass
class _Stream:
    """One speculative request mid-flight (the round state machine)."""

    req_id: int
    slot: int
    n_prompt: int
    max_new: int
    hard_cap: int
    generated: list[int]
    t_cur: int
    st_cur: int
    n_own: int
    finished: bool = False
    disabled: bool = False
    # Set when the auto-disable edge handed the slot back to the engine:
    # the request is a NORMAL engine request from then on and its
    # Finished record arrives through the caller's own
    # step_fused()/decode_fused() harvest, never through advance().
    handed_off: bool = False
    ewma: float | None = None
    rounds: int = 0
    pending: Any = None  # _Proposal | _HiddenBlock | None
    t0: float = dataclasses.field(default_factory=time.perf_counter)
    seg: dict[str, float] = dataclasses.field(
        default_factory=lambda: {"draft": 0.0, "verify": 0.0, "rollback": 0.0}
    )
    overlapped: int = 0


class SpeculativeDecoder:
    """Speculative decoding over one engine + one draft arm."""

    def __init__(
        self,
        engine,  # InferenceEngine (not annotated: avoids an import cycle)
        draft_params: Params | None = None,
        draft_cfg: LlamaConfig | None = None,
        *,
        k: int = 4,
        arm: str = "draft",
        hidden_head: Params | None = None,
        hidden_seed: int = 0,
        disable_threshold: float = 0.3,
        ewma_alpha: float = 0.3,
        min_rounds: int = 4,
    ) -> None:
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        if not 0.0 <= disable_threshold <= 1.0:
            raise ValueError(
                f"disable_threshold must be in [0, 1], got {disable_threshold}"
            )
        if arm not in ("draft", "hidden"):
            raise ValueError(f"unknown spec arm {arm!r}")
        tok_vocab = engine.tokenizer.vocab_size
        self.engine = engine
        self.arm = arm
        self.k = int(k)
        self.disable_threshold = float(disable_threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.min_rounds = int(min_rounds)
        self.stats = SpecStats()
        self._streams: dict[int, _Stream] = {}  # slot -> open stream
        if arm == "draft":
            if draft_params is None or draft_cfg is None:
                raise ValueError("arm='draft' needs draft_params + draft_cfg")
            if draft_cfg.vocab_size < tok_vocab:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} < tokenizer vocab "
                    f"{tok_vocab} — the draft cannot propose every legal token"
                )
            # Draft masks the same undecodable tail as the target (a draft
            # with a wider padded vocab must never propose past the
            # tokenizer).
            draft_limit = (
                tok_vocab if tok_vocab < draft_cfg.vocab_size else None
            )
            self.draft: DraftRunner | None = DraftRunner(
                draft_params, draft_cfg, vocab_limit=draft_limit
            )
            self.hidden_head: Params | None = None
            self._hidden_verify = None
        else:
            self.draft = None
            self.hidden_head = (
                hidden_head
                if hidden_head is not None
                else init_hidden_transfer(
                    jax.random.PRNGKey(hidden_seed), engine.cfg, self.k
                )
            )
            from k8s_llm_scheduler_tpu.spec.hidden import _hidden_verify_impl

            self._hidden_verify = jax.jit(
                functools.partial(
                    _hidden_verify_impl,
                    vocab_limit=engine._vocab_limit,
                    prefix_impl=engine.prefix_attn_impl,
                ),
                static_argnums=(1, 23, 24, 25),
                donate_argnums=(8, 9),
            )
        self._verify = jax.jit(
            functools.partial(
                _verify_impl,
                vocab_limit=engine._vocab_limit,
                prefix_impl=engine.prefix_attn_impl,
            ),
            static_argnums=(1, 22, 23),
            donate_argnums=(7, 8),
        )

    # ------------------------------------------------------------- plumbing
    def supports(self, prompt_ids: list[int], max_new_tokens: int) -> bool:
        """Whether this request can take the speculative path (the caller
        falls back to plain decode when not — never an error)."""
        eng = self.engine
        total = eng.prefix_len + len(prompt_ids)
        # The draft prefills the full context single-shot; cap it at the
        # engine's largest bucket like every other prefill. (The hidden
        # arm keeps the same bound: its block geometry rides the same
        # paged admission limits.)
        return total <= eng.prefill_buckets[-1]

    def _grammar_mode(self) -> tuple[str, jax.Array]:
        """(grammar impl for this dispatch, dense table or dummy).

        Greedy constrained verification uses the DENSE transition table
        when the engine's grammar exports one (the fused runtime's table
        — engine.dense_grammar()); the rejection sampler's proposal
        distributions live in K-space, so sampling mode keeps the sparse
        tables, as does a grammar past the dense-table byte cap."""
        eng = self.engine
        if not eng._constrained:
            return "none", eng._fused_dummy
        if eng.temperature == 0.0:
            dense = eng.dense_grammar()
            if dense is not None:
                return "dense", dense
        return "sparse", eng._fused_dummy

    def _round_io(self, slot: int, n_own: int, w: int, hard_cap: int):
        """Host-side page bookkeeping for one round: grow the slot to cover
        the block, then map each block position to (page, offset). Positions
        past `hard_cap` (draft tokens that could never be kept within the
        budget) route to the reserved scratch page 0."""
        eng = self.engine
        ps = eng.kv.page_size
        eng.kv.ensure_capacity(slot, min(n_own + w, hard_cap))
        pages = eng.kv.slot_pages(slot)
        page_ids = np.zeros(w, dtype=np.int32)
        offs = np.zeros(w, dtype=np.int32)
        for i, p in enumerate(range(n_own, n_own + w)):
            blk = p // ps
            if p < hard_cap and blk < len(pages):
                page_ids[i] = pages[blk]
                offs[i] = p % ps
        return jnp.asarray(page_ids), jnp.asarray(offs)

    def _propose_from(self, tok, pos: int, state, rng) -> _Proposal:
        """One fused draft proposal anchored at (tok @ pos, state) — host
        ints for a fresh round, device scalars for an AHEAD round."""
        eng = self.engine
        toks, states, idxs, logits = self.draft.propose(
            tok, pos, state,
            eng._sp_tokens, eng._sp_next, eng.tokenizer.pad_id,
            rng, eng.temperature, self.k, eng._constrained,
        )
        return _Proposal(
            anchor_tok=jnp.asarray(tok, dtype=jnp.int32),
            anchor_st=jnp.asarray(state, dtype=jnp.int32),
            pos=pos, toks=toks, states=states, idxs=idxs, logits=logits,
        )

    # --------------------------------------------------------------- stream
    def start(self, prompt_ids: list[int], max_new_tokens: int) -> _Stream:
        """Admit a request and open its speculative stream.

        Admission reuses the engine's own batched program (prompt KV lands
        in the slot's pages, the first token samples exactly as plain
        decode), then the slot is marked EXTERNAL and deactivated in the
        engine's decode batch: fused chunks for other slots keep
        dispatching while this stream drives its own rounds — the
        coexistence that replaced `engine.fused_hold`."""
        if self._streams:
            raise RuntimeError("one speculative stream at a time")
        eng = self.engine
        req_id = eng.add_request(prompt_ids, max_new_tokens)
        slot = next(s for s, r in eng._by_slot.items() if r.req_id == req_id)
        try:
            first_np, act_np, st_np = jax.device_get(
                (eng._first_d, eng._act_d, eng._st_d)
            )
            eng.stats["syncs"] += 1
            # Take the slot OUT of the engine's decode batch (after the
            # state fetch — deactivation clobbers the admission-time
            # active flag).
            req = eng._by_slot[slot]
            req.external = True
            eng._act_d = eng._act_d.at[slot].set(False)
            eng._budget_d = eng._budget_d.at[slot].set(0)
            eng._act_np[slot] = False
            eng._budget_np[slot] = 0

            n_prompt = len(prompt_ids)
            s = _Stream(
                req_id=req_id,
                slot=slot,
                n_prompt=n_prompt,
                max_new=max_new_tokens,
                hard_cap=n_prompt + max_new_tokens + 1,
                generated=[int(first_np[slot])],
                t_cur=int(first_np[slot]),
                st_cur=int(st_np[slot]),
                n_own=n_prompt,
                finished=not bool(act_np[slot]),
            )
            # Release the admission-time full decode reservation: the
            # spec loop grows per round and truncate() rolls rejected
            # tails back, which only means anything if the tail pages
            # are actually freeable.
            eng.kv.truncate(slot, s.n_own)
            if (
                self.arm == "draft"
                and not s.finished
                and max_new_tokens > 1
            ):
                prefix = eng._prefix or eng._get_empty_prefix()
                ctx = list(prefix.token_ids) + list(prompt_ids)
                t_d = time.perf_counter()
                with recorder.phase("spec_draft_prefill"):
                    # +2K+4 slack: the AHEAD proposal writes up to K+1
                    # past the block it anticipates.
                    self.draft.begin(
                        ctx, eng.tokenizer.pad_id,
                        extra=max_new_tokens + 2 * self.k + 4,
                    )
                s.seg["draft"] += time.perf_counter() - t_d
        except Exception:
            # A failed start must not leak the slot as an orphaned
            # external request (every harvest path skips external — no
            # later recovery path would ever free it).
            eng.release_slot(slot)
            raise
        self._streams[slot] = s
        return s

    def advance(self, s: _Stream):
        """Run ONE speculative round (or the terminal transition).

        Returns the Finished record once the request completes through
        the speculative path, else None. Callers may interleave
        engine.step_fused() between advances — spec rounds and fused
        chunks share one dispatch pipeline. On the auto-disable edge the
        slot HANDS BACK to the engine (`s.handed_off` flips True): the
        request finishes like any other through the caller's own
        step_fused()/decode_fused() harvest — advance() never consumes
        (and could otherwise silently drop) coexisting slots' Finished
        records. A failed round tears the stream down (slot + pages
        released, the one-stream guard cleared) before re-raising."""
        if s.handed_off:
            raise RuntimeError(
                "stream handed off to the engine (auto-disable); harvest "
                "its Finished via step_fused/decode_fused"
            )
        if self._streams.get(s.slot) is not s:
            # finished / torn down: the slot may already serve another
            # request — touching it again could release an innocent
            # coexisting stream's state
            raise RuntimeError("speculative stream is closed")
        try:
            if s.finished or len(s.generated) >= s.max_new:
                return self._finish(s)
            if not s.disabled:
                if self.arm == "hidden":
                    self._round_hidden(s)
                else:
                    self._round_draft(s)
            if s.finished or len(s.generated) >= s.max_new:
                return self._finish(s)
            if s.disabled:
                self._hand_off(s)
            return None
        except Exception:
            self._streams.pop(s.slot, None)
            if (
                s.slot in self.engine._by_slot
                and self.engine._by_slot[s.slot].req_id == s.req_id
            ):
                self.engine.release_slot(s.slot)
            raise

    # -------------------------------------------------------- draft rounds
    def _round_draft(self, s: _Stream) -> None:
        eng = self.engine
        K = self.k
        w = K + 1
        pad = eng.tokenizer.pad_id
        prefix = eng._prefix or eng._get_empty_prefix()
        abs_pos = eng.prefix_len + s.n_own
        grammar, dense_tbl = self._grammar_mode()

        prop = s.pending
        s.pending = None
        overlapped = prop is not None and prop.pos == abs_pos
        if not overlapped:
            if prop is not None:
                self.stats.ahead_wasted += 1
            t_d = time.perf_counter()
            eng._rng, r_draft = jax.random.split(eng._rng)
            with recorder.phase("spec_draft"):
                prop = self._propose_from(
                    s.t_cur, abs_pos, s.st_cur, r_draft
                )
            s.seg["draft"] += time.perf_counter() - t_d

        blk_tok = jnp.concatenate([prop.anchor_tok[None], prop.toks[:K]])
        mask_states = jnp.concatenate(
            [prop.anchor_st[None], prop.states[:K]]
        )[:w]
        positions = jnp.arange(abs_pos, abs_pos + w, dtype=jnp.int32)
        page_ids, offs = self._round_io(s.slot, s.n_own, w, s.hard_cap)
        table_row = eng.kv.page_tables()[s.slot][None, :]

        t_v = time.perf_counter()
        eng._rng, r_verify = jax.random.split(eng._rng)
        with recorder.phase("spec_verify"):
            a_d, t_next_d, st_next_d, eng.kv.k, eng.kv.v = self._verify(
                eng.params, eng.cfg,
                blk_tok, positions,
                prefix.k, prefix.v, jnp.int32(prefix.length),
                eng.kv.k, eng.kv.v,
                table_row, jnp.int32(s.n_own), page_ids, offs,
                mask_states, prop.idxs, prop.logits,
                eng._sp_tokens, eng._sp_next, dense_tbl,
                jnp.int32(pad),
                r_verify, jnp.float32(eng.temperature),
                grammar, eng.temperature == 0.0,
            )
        s.seg["verify"] += time.perf_counter() - t_v

        # AHEAD proposal for round n+1, enqueued BEFORE the round's fetch:
        # the draft continues its own chain through the bonus-token guess
        # while the target verify (already dispatched) runs — this is the
        # overlap. Skipped when the budget could never use it or the
        # draft buffer would overflow.
        ahead = None
        ahead_pos = abs_pos + K + 1
        remaining = s.max_new - len(s.generated)
        if remaining > K + 1 and ahead_pos + K + 1 <= self.draft.capacity:
            t_d = time.perf_counter()
            eng._rng, r_ahead = jax.random.split(eng._rng)
            with recorder.phase("spec_draft"):
                ahead = self._propose_from(
                    prop.toks[K], ahead_pos, prop.states[K], r_ahead
                )
            s.seg["draft"] += time.perf_counter() - t_d

        # THE round's one host fetch: accept verdict + the block's token
        # values (the ahead proposal's outputs stay device-resident).
        t_v = time.perf_counter()
        a_np, t_next_np, st_next_np, toks_np, states_np = jax.device_get(
            (a_d, t_next_d, st_next_d, prop.toks, prop.states)
        )
        eng.stats["syncs"] += 1
        s.seg["verify"] += time.perf_counter() - t_v

        a = int(a_np)
        t_next, st_next = int(t_next_np), int(st_next_np)
        if overlapped:
            self.stats.overlapped_rounds += 1
            s.overlapped += 1
        self._resolve_round(
            s, a, t_next, st_next,
            [(int(toks_np[i]), int(states_np[i])) for i in range(a)],
        )
        # Adopt the ahead block when the chain it anticipated is exactly
        # the chain that happened: full accept AND the bonus token (and
        # its DFA state) match the draft's guess.
        if (
            ahead is not None
            and not s.finished
            and not s.disabled
            and len(s.generated) < s.max_new
            and a == K
            and t_next == int(toks_np[K])
            and st_next == int(states_np[K])
            and eng.prefix_len + s.n_own == ahead.pos
        ):
            s.pending = ahead
        elif ahead is not None:
            self.stats.ahead_wasted += 1

    # ------------------------------------------------------- hidden rounds
    def _round_hidden(self, s: _Stream) -> None:
        eng = self.engine
        K = self.k
        pad = eng.tokenizer.pad_id
        prefix = eng._prefix or eng._get_empty_prefix()
        abs_pos = eng.prefix_len + s.n_own
        grammar, dense_tbl = self._grammar_mode()

        pend = s.pending
        s.pending = None
        if pend is not None and pend.pos == abs_pos:
            w = K + 1
            blk_tok = jnp.concatenate(
                [jnp.asarray([s.t_cur], dtype=jnp.int32), pend.toks]
            )
            mask_states = jnp.concatenate(
                [jnp.asarray([s.st_cur], dtype=jnp.int32), pend.states]
            )[:w]
            choice_idx, q_logits = pend.idxs, pend.logits
            drafts = [
                (int(pend.toks_np[i]), int(pend.states_np[i]))
                for i in range(K)
            ]
            overlapped = True
        else:
            # Bootstrap geometry (W=1): no proposals to verify yet — the
            # program processes the current token, samples its successor,
            # and produces the first transfer-head proposal block.
            w = 1
            blk_tok = jnp.asarray([s.t_cur], dtype=jnp.int32)
            mask_states = jnp.asarray([s.st_cur], dtype=jnp.int32)
            choice_idx = jnp.zeros((0,), dtype=jnp.int32)
            q_logits = jnp.zeros((0, 1), dtype=jnp.float32)
            drafts = []
            overlapped = False
        positions = jnp.arange(abs_pos, abs_pos + w, dtype=jnp.int32)
        page_ids, offs = self._round_io(s.slot, s.n_own, w, s.hard_cap)
        table_row = eng.kv.page_tables()[s.slot][None, :]

        t_v = time.perf_counter()
        eng._rng, r_verify = jax.random.split(eng._rng)
        with recorder.phase("spec_verify"):
            (
                a_d, t_next_d, st_next_d,
                g_toks_d, g_states_d, g_idx_d, g_logits_d,
                eng.kv.k, eng.kv.v,
            ) = self._hidden_verify(
                eng.params, eng.cfg, self.hidden_head,
                blk_tok, positions,
                prefix.k, prefix.v, jnp.int32(prefix.length),
                eng.kv.k, eng.kv.v,
                table_row, jnp.int32(s.n_own), page_ids, offs,
                mask_states, choice_idx, q_logits,
                eng._sp_tokens, eng._sp_next, dense_tbl,
                jnp.int32(pad),
                r_verify, jnp.float32(eng.temperature),
                grammar, eng.temperature == 0.0, K,
            )
        # The round's one fetch: verdict + the NEXT block's guess values
        # (the guesses' device arrays stay resident for round n+1's
        # dispatch — host copies serve the emit path without a 2nd sync).
        a_np, t_next_np, st_next_np, g_toks_np, g_states_np = jax.device_get(
            (a_d, t_next_d, st_next_d, g_toks_d, g_states_d)
        )
        eng.stats["syncs"] += 1
        s.seg["verify"] += time.perf_counter() - t_v

        a = int(a_np)
        t_next, st_next = int(t_next_np), int(st_next_np)
        if overlapped:
            # Proposals were computed inside the PREVIOUS round's program
            # — the propose stream is fully hidden behind the verify.
            self.stats.overlapped_rounds += 1
            s.overlapped += 1
            self._resolve_round(s, a, t_next, st_next, drafts[:a])
        else:
            # Bootstrap: one target-sampled token, no proposals verified.
            self._resolve_round(
                s, a, t_next, st_next, [], count_round=False
            )
        if (
            not s.finished
            and not s.disabled
            and s.max_new - len(s.generated) > 1
        ):
            s.pending = _HiddenBlock(
                pos=self.engine.prefix_len + s.n_own,
                toks=g_toks_d, states=g_states_d,
                idxs=g_idx_d, logits=g_logits_d,
                toks_np=np.asarray(g_toks_np),
                states_np=np.asarray(g_states_np),
            )

    # ------------------------------------------------------------- resolve
    def _resolve_round(
        self,
        s: _Stream,
        a: int,
        t_next: int,
        st_next: int,
        accepted: list[tuple[int, int]],
        count_round: bool = True,
    ) -> None:
        """Emit the round's target-consistent tokens, roll back the
        rejected tail's pages, and update the acceptance EWMA."""
        eng = self.engine
        eos = eng.tokenizer.eos_id
        done_state = int(eng._done_state)
        if count_round:
            s.rounds += 1
            self.stats.rounds += 1
            self.stats.proposed += self.k
            self.stats.accepted += a

        t_r = time.perf_counter()
        cand = list(accepted)
        cand.append((t_next, st_next))
        for tok, stt in cand:
            if len(s.generated) >= s.max_new:
                break
            s.generated.append(tok)
            self.stats.emitted += 1
            if tok == eos or stt == done_state:
                s.finished = True
                break
            s.t_cur, s.st_cur = tok, stt
        # n_own counts tokens whose KV is resident: t_cur's KV lands only
        # when it is processed next round, so the resident count is
        # prompt + (emitted - 1).
        s.n_own = s.n_prompt + len(s.generated) - 1
        # Paged-KV rollback: free the rejected tail's pages.
        eng.kv.truncate(s.slot, s.n_own)
        s.seg["rollback"] += time.perf_counter() - t_r

        if count_round:
            rate = a / self.k
            s.ewma = (
                rate
                if s.ewma is None
                else self.ewma_alpha * rate + (1 - self.ewma_alpha) * s.ewma
            )
            # PER-REQUEST warmup (s.rounds, not the decoder-global round
            # counter): every request gets min_rounds of EWMA settling
            # before it can disable — a global counter would let any
            # request after the first disable on its very first bad round.
            if (
                s.rounds >= self.min_rounds
                and not s.finished
                and s.ewma < self.disable_threshold
            ):
                s.disabled = True
                self.stats.disables += 1

    # ------------------------------------------------------------- generate
    def generate(self, prompt_ids: list[int], max_new_tokens: int = 200):
        """Speculative replacement for the engine's plain generate():
        greedy output is token-identical to plain decode, sampling output
        follows the target distribution exactly (spec/verify.py)."""
        eng = self.engine
        if not self.supports(prompt_ids, max_new_tokens):
            self.stats.unsupported_requests += 1
            return eng.generate(prompt_ids, max_new_tokens, use_spec=False)
        self.stats.requests += 1
        from k8s_llm_scheduler_tpu.observability import spans

        s0 = self.stats
        before = (s0.proposed, s0.accepted, s0.rounds, s0.disables)
        s = self.start(prompt_ids, max_new_tokens)
        try:
            with spans.span("spec_decode") as sp:
                fin = None
                while fin is None and not s.handed_off:
                    fin = self.advance(s)
                if fin is None:
                    # Auto-disable handed the slot to the engine: finish
                    # it through the fused runtime. Single-request
                    # surface — same Finished-filtering semantics as
                    # engine.generate().
                    with recorder.phase("spec_fallback"):
                        while fin is None:
                            for f in eng.step_fused():
                                if f.req_id == s.req_id:
                                    fin = f
                if sp is not None:
                    sp.attrs.update(
                        arm=self.arm,
                        proposed=s0.proposed - before[0],
                        accepted=s0.accepted - before[1],
                        rejected=(s0.proposed - before[0])
                        - (s0.accepted - before[1]),
                        rounds=s0.rounds - before[2],
                        disabled=bool(s0.disables - before[3]),
                    )
            return fin
        except Exception:
            # Mirror add_requests' rollback: a failed round must not leak
            # the slot or its pages (no later recovery path would — the
            # request never reaches step()'s teardown).
            self._streams.pop(s.slot, None)
            if s.slot in eng._by_slot:
                eng.release_slot(s.slot)
            raise

    # ------------------------------------------------------------- teardown
    def _profile_stream(self, s: _Stream, disabled: bool) -> None:
        prof = self.engine.profiler
        if prof is None:
            return
        prof.on_spec(
            wall_s=time.perf_counter() - s.t0,
            draft_s=s.seg["draft"],
            verify_s=s.seg["verify"],
            rollback_s=s.seg["rollback"],
            rounds=s.rounds,
            overlapped_rounds=s.overlapped,
            tokens=max(len(s.generated) - 1, 0),
            arm=self.arm,
            disabled=disabled,
        )

    def _finish(self, s: _Stream):
        """Complete the request: free the slot and build Finished exactly
        like the plain step() path does."""
        from k8s_llm_scheduler_tpu.engine.engine import Finished

        eng = self.engine
        req = eng._by_slot[s.slot]
        self._streams.pop(s.slot, None)
        eng.release_slot(s.slot)
        ids = s.generated[: s.max_new]
        # First token is accounted like the plain path (not a decode token).
        eng.stats["decode_tokens"] += max(len(ids) - 1, 0)
        eng.stats["completed"] += 1
        self._profile_stream(s, disabled=False)
        return Finished(
            req_id=s.req_id,
            token_ids=ids,
            text=eng.tokenizer.decode(ids),
            latency_ms=(time.perf_counter() - req.submitted_at) * 1000.0,
        )

    def _hand_off(self, s: _Stream) -> None:
        """Auto-disable hand-off: restore the slot's device-resident
        decode state and hand it BACK to the engine's decode batch
        (external flag cleared — the disable edge re-arms the FUSED
        path, it never strands the slot on the slow chunked loop). The
        request finishes like any other engine request: the caller's own
        step_fused()/decode_fused() harvest returns its Finished record
        — driving the engine from HERE would consume (and drop)
        coexisting slots' completions out from under the caller."""
        eng = self.engine
        self.stats.fallback_requests += 1
        self._streams.pop(s.slot, None)
        self._profile_stream(s, disabled=True)
        remaining = s.max_new - len(s.generated)
        req = eng._by_slot[s.slot]
        req.generated = list(s.generated)
        req.first_pending = False
        req.external = False
        eng.kv.ensure_capacity(s.slot, s.n_own + remaining + 1)
        eng._tok_d = eng._tok_d.at[s.slot].set(s.t_cur)
        eng._pos_d = eng._pos_d.at[s.slot].set(eng.prefix_len + s.n_own)
        eng._act_d = eng._act_d.at[s.slot].set(True)
        eng._st_d = eng._st_d.at[s.slot].set(s.st_cur)
        eng._budget_d = eng._budget_d.at[s.slot].set(remaining)
        eng._act_np[s.slot] = True
        eng._budget_np[s.slot] = remaining
        # The spec-emitted tokens are already in req.generated; the plain
        # path's completion accounting takes over from here.
        eng.stats["decode_tokens"] += max(len(s.generated) - 1, 0)
        s.handed_off = True

    # ----------------------------------------------------------------- swap
    def on_swap(self) -> None:
        """Engine hot-swap hook (engine.swap_params calls this BEFORE
        installing new weights): roll back every open stream's
        speculative tail via the grammar-safe PagedKVCache.truncate and
        drop device-resident proposal blocks — they were computed under
        the superseded weights and must never seed a post-swap round.
        Already-emitted tokens stand (identical-params swaps are the only
        mid-stream-legal kind, exactly the paged in-flight contract
        engine.swap_params documents); the stream re-proposes fresh from
        its last verified token on the next advance."""
        for s in self._streams.values():
            self.engine.kv.truncate(s.slot, s.n_own)
            if s.pending is not None:
                s.pending = None
                self.stats.ahead_wasted += 1
            self.stats.swap_rollbacks += 1

    @property
    def open_streams(self) -> int:
        return len(self._streams)
