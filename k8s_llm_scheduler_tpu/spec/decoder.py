"""SpeculativeDecoder: draft-propose / target-verify over the paged path.

Orchestrates one request at a time through the engine's general paged-decode
state (engine/engine.py `generate()` routes here when a decoder is
attached): admission reuses the engine's own batched-admission program (so
prompt prefill and the first sampled token are bit-identical to plain
decode), then each round is

    draft.propose (1 dispatch, K tokens)
    -> _verify_impl (1 dispatch: target scores K+1 positions, accepts)
    -> ONE host fetch
    -> kv_cache.truncate rolls back the rejected tail's pages

Robustness is part of the loop, not an afterthought:

- A per-request acceptance-rate EWMA auto-disables speculation when the
  draft stops earning its keep (below `disable_threshold` after
  `min_rounds`); the request hands off MID-STREAM to the engine's plain
  fused-chunk decode path — device slot state is restored and
  `engine.step()` finishes the request, so a bad draft costs a few wasted
  rounds, never a broken or slow completion.
- Acceptance rate, emitted-tokens-per-round, and disable events export
  through the engine's stats (observability/metrics.py serves them at
  /metrics); draft/verify phases are span'd through observability/trace.py.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import Params
from k8s_llm_scheduler_tpu.observability.trace import recorder
from k8s_llm_scheduler_tpu.spec.draft import DraftRunner
from k8s_llm_scheduler_tpu.spec.verify import _verify_impl


@dataclasses.dataclass
class SpecStats:
    requests: int = 0
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0
    emitted: int = 0
    disables: int = 0
    fallback_requests: int = 0
    unsupported_requests: int = 0

    def snapshot(self) -> dict[str, Any]:
        out = dataclasses.asdict(self)
        out["acceptance_rate"] = (
            self.accepted / self.proposed if self.proposed else 0.0
        )
        out["tokens_per_round"] = (
            self.emitted / self.rounds if self.rounds else 0.0
        )
        return out


class SpeculativeDecoder:
    """Speculative decoding over one engine + one draft model."""

    def __init__(
        self,
        engine,  # InferenceEngine (not annotated: avoids an import cycle)
        draft_params: Params,
        draft_cfg: LlamaConfig,
        *,
        k: int = 4,
        disable_threshold: float = 0.3,
        ewma_alpha: float = 0.3,
        min_rounds: int = 4,
    ) -> None:
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        if not 0.0 <= disable_threshold <= 1.0:
            raise ValueError(
                f"disable_threshold must be in [0, 1], got {disable_threshold}"
            )
        tok_vocab = engine.tokenizer.vocab_size
        if draft_cfg.vocab_size < tok_vocab:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} < tokenizer vocab "
                f"{tok_vocab} — the draft cannot propose every legal token"
            )
        self.engine = engine
        self.k = int(k)
        self.disable_threshold = float(disable_threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.min_rounds = int(min_rounds)
        self.stats = SpecStats()
        # Draft masks the same undecodable tail as the target (a draft with
        # a wider padded vocab must never propose past the tokenizer).
        draft_limit = tok_vocab if tok_vocab < draft_cfg.vocab_size else None
        self.draft = DraftRunner(
            draft_params, draft_cfg, vocab_limit=draft_limit
        )
        self._verify = jax.jit(
            functools.partial(
                _verify_impl,
                vocab_limit=engine._vocab_limit,
                prefix_impl=engine.prefix_attn_impl,
            ),
            static_argnums=(1, 21, 22),
            donate_argnums=(7, 8),
        )

    # ------------------------------------------------------------- plumbing
    def supports(self, prompt_ids: list[int], max_new_tokens: int) -> bool:
        """Whether this request can take the speculative path (the caller
        falls back to plain decode when not — never an error)."""
        eng = self.engine
        total = eng.prefix_len + len(prompt_ids)
        # The draft prefills the full context single-shot; cap it at the
        # engine's largest bucket like every other prefill.
        return total <= eng.prefill_buckets[-1]

    def _round_io(self, slot: int, n_own: int, w: int, hard_cap: int):
        """Host-side page bookkeeping for one round: grow the slot to cover
        the block, then map each block position to (page, offset). Positions
        past `hard_cap` (draft tokens that could never be kept within the
        budget) route to the reserved scratch page 0."""
        eng = self.engine
        ps = eng.kv.page_size
        eng.kv.ensure_capacity(slot, min(n_own + w, hard_cap))
        pages = eng.kv.slot_pages(slot)
        page_ids = np.zeros(w, dtype=np.int32)
        offs = np.zeros(w, dtype=np.int32)
        for i, p in enumerate(range(n_own, n_own + w)):
            blk = p // ps
            if p < hard_cap and blk < len(pages):
                page_ids[i] = pages[blk]
                offs[i] = p % ps
        return jnp.asarray(page_ids), jnp.asarray(offs)

    # ------------------------------------------------------------- generate
    def generate(self, prompt_ids: list[int], max_new_tokens: int = 200):
        """Speculative replacement for the engine's plain generate():
        greedy output is token-identical to plain decode, sampling output
        follows the target distribution exactly (spec/verify.py)."""
        eng = self.engine
        if not self.supports(prompt_ids, max_new_tokens):
            self.stats.unsupported_requests += 1
            return eng.generate(
                prompt_ids, max_new_tokens, use_spec=False
            )
        self.stats.requests += 1
        # Admission through the engine's own program: prompt KV lands in the
        # slot's pages and the first token samples exactly as plain decode.
        req_id = eng.add_request(prompt_ids, max_new_tokens)
        slot = next(s for s, r in eng._by_slot.items() if r.req_id == req_id)
        from k8s_llm_scheduler_tpu.observability import spans

        # one span for the whole speculative decode, carrying the round's
        # accept/reject deltas — per-round spans would be dozens per request
        s0 = self.stats
        before = (s0.proposed, s0.accepted, s0.rounds, s0.disables)
        # Explicit NON-FUSED interop (engine/fused/): a speculative round
        # diverges the slot's device decode state from the host mirrors
        # mid-round (truncate/restore), so fused chunks must not run while
        # one is open — engine.step_fused checks fused_hold and falls back
        # to the plain chunked path, which is also what _fallback drives.
        eng.fused_hold += 1
        try:
            with spans.span("spec_decode") as sp:
                out = self._generate_admitted(
                    req_id, slot, prompt_ids, max_new_tokens
                )
                if sp is not None:
                    sp.attrs.update(
                        proposed=s0.proposed - before[0],
                        accepted=s0.accepted - before[1],
                        rejected=(s0.proposed - before[0])
                        - (s0.accepted - before[1]),
                        rounds=s0.rounds - before[2],
                        disabled=bool(s0.disables - before[3]),
                    )
            return out
        except Exception:
            # Mirror add_requests' rollback: a failed round must not leak
            # the slot or its pages (no later recovery path would — the
            # request never reaches step()'s teardown).
            if slot in eng._by_slot:
                eng.release_slot(slot)
            raise
        finally:
            eng.fused_hold -= 1

    def _generate_admitted(
        self,
        req_id: int,
        slot: int,
        prompt_ids: list[int],
        max_new_tokens: int,
    ):
        eng = self.engine
        first_np, act_np, st_np = jax.device_get(
            (eng._first_d, eng._act_d, eng._st_d)
        )
        eng.stats["syncs"] += 1
        t_cur = int(first_np[slot])
        st_cur = int(st_np[slot])
        generated = [t_cur]
        finished = not bool(act_np[slot])
        eos = eng.tokenizer.eos_id
        pad = eng.tokenizer.pad_id
        done_state = int(eng._done_state)
        prefix = eng._prefix or eng._get_empty_prefix()
        n_prompt = len(prompt_ids)
        n_own = n_prompt  # tokens with valid KV in the slot's pages
        # Release the admission-time full decode reservation: the spec loop
        # grows per round and truncate() rolls rejected tails back, which
        # only means anything if the tail pages are actually freeable.
        eng.kv.truncate(slot, n_own)
        hard_cap = n_prompt + max_new_tokens + 1
        w = self.k + 1
        ewma: float | None = None
        req_rounds = 0
        disabled = False

        if not finished and max_new_tokens > 1:
            ctx = list(prefix.token_ids) + list(prompt_ids)
            with recorder.phase("spec_draft_prefill"):
                self.draft.begin(
                    ctx, pad, extra=max_new_tokens + self.k + 2
                )

        while not finished and len(generated) < max_new_tokens:
            if disabled:
                return self._fallback(
                    req_id, slot, generated, t_cur, st_cur, n_own,
                    max_new_tokens,
                )
            abs_pos = eng.prefix_len + n_own
            eng._rng, r_draft, r_verify = jax.random.split(eng._rng, 3)
            with recorder.phase("spec_draft"):
                d_toks, d_states, d_idx, d_logits = self.draft.propose(
                    t_cur, abs_pos, st_cur,
                    eng._sp_tokens, eng._sp_next, pad,
                    r_draft, eng.temperature, self.k, eng._constrained,
                )
            blk_tok = jnp.concatenate(
                [jnp.asarray([t_cur], dtype=jnp.int32), d_toks]
            )
            mask_states = jnp.concatenate(
                [jnp.asarray([st_cur], dtype=jnp.int32), d_states]
            )[:w]
            positions = jnp.arange(abs_pos, abs_pos + w, dtype=jnp.int32)
            page_ids, offs = self._round_io(slot, n_own, w, hard_cap)
            table_row = eng.kv.page_tables()[slot][None, :]
            with recorder.phase("spec_verify"):
                a_d, t_next_d, st_next_d, eng.kv.k, eng.kv.v = self._verify(
                    eng.params, eng.cfg,
                    blk_tok, positions,
                    prefix.k, prefix.v, jnp.int32(prefix.length),
                    eng.kv.k, eng.kv.v,
                    table_row, jnp.int32(n_own), page_ids, offs,
                    mask_states, d_idx, d_logits,
                    eng._sp_tokens, eng._sp_next,
                    jnp.int32(pad),
                    r_verify, jnp.float32(eng.temperature),
                    eng._constrained, eng.temperature == 0.0,
                )
                a, t_next, st_next, d_toks_np, d_states_np = jax.device_get(  # graftlint: ok[device-sync-in-loop] — the speculative round's ONE host fetch per K proposed tokens: accept/rollback is a host decision (kv.truncate frees pages); bounded at 1 sync per round by design
                    (a_d, t_next_d, st_next_d, d_toks, d_states)
                )
            eng.stats["syncs"] += 1
            a = int(a)
            req_rounds += 1
            self.stats.rounds += 1
            self.stats.proposed += self.k
            self.stats.accepted += a

            # Emit: the accepted draft prefix, then the verifier's token
            # (correction or bonus). All are target-consistent; trim to
            # budget and stop at EOS / DFA done.
            cand = [(int(d_toks_np[i]), int(d_states_np[i])) for i in range(a)]
            cand.append((int(t_next), int(st_next)))
            for tok, stt in cand:
                if len(generated) >= max_new_tokens:
                    break
                generated.append(tok)
                self.stats.emitted += 1
                if tok == eos or stt == done_state:
                    finished = True
                    break
                t_cur, st_cur = tok, stt
            # n_own counts tokens whose KV is resident: t_cur's KV lands
            # only when it is processed next round, so the resident count
            # is prompt + (emitted - 1).
            n_own = n_prompt + len(generated) - 1
            # Paged-KV rollback: free the rejected tail's pages.
            eng.kv.truncate(slot, n_own)

            rate = a / self.k
            ewma = (
                rate
                if ewma is None
                else self.ewma_alpha * rate + (1 - self.ewma_alpha) * ewma
            )
            # PER-REQUEST warmup (req_rounds, not the decoder-global round
            # counter): every request gets min_rounds of EWMA settling
            # before it can disable — a global counter would let any
            # request after the first disable on its very first bad round.
            if (
                req_rounds >= self.min_rounds
                and not finished
                and ewma < self.disable_threshold
            ):
                disabled = True
                self.stats.disables += 1

        return self._finish(req_id, slot, generated, max_new_tokens)

    # ------------------------------------------------------------- teardown
    def _finish(
        self, req_id: int, slot: int, generated: list[int], max_new: int
    ):
        """Complete the request: free the slot and build Finished exactly
        like the plain step() path does."""
        from k8s_llm_scheduler_tpu.engine.engine import Finished

        eng = self.engine
        req = eng._by_slot[slot]
        eng.release_slot(slot)
        ids = generated[:max_new]
        # First token is accounted like the plain path (not a decode token).
        eng.stats["decode_tokens"] += max(len(ids) - 1, 0)
        eng.stats["completed"] += 1
        return Finished(
            req_id=req_id,
            token_ids=ids,
            text=eng.tokenizer.decode(ids),
            latency_ms=(time.perf_counter() - req.submitted_at) * 1000.0,
        )

    def _fallback(
        self,
        req_id: int,
        slot: int,
        generated: list[int],
        t_cur: int,
        st_cur: int,
        n_own: int,
        max_new: int,
    ):
        """Auto-disable hand-off: restore the slot's device-resident decode
        state and let the engine's plain fused-chunk path finish the
        request (engine/engine.py step())."""
        eng = self.engine
        self.stats.fallback_requests += 1
        remaining = max_new - len(generated)
        req = eng._by_slot[slot]
        req.generated = list(generated)
        req.first_pending = False
        eng.kv.ensure_capacity(slot, n_own + remaining + 1)
        eng._tok_d = eng._tok_d.at[slot].set(t_cur)
        eng._pos_d = eng._pos_d.at[slot].set(eng.prefix_len + n_own)
        eng._act_d = eng._act_d.at[slot].set(True)
        eng._st_d = eng._st_d.at[slot].set(st_cur)
        eng._budget_d = eng._budget_d.at[slot].set(remaining)
        eng._act_np[slot] = True
        eng._budget_np[slot] = remaining
        # The spec-emitted tokens are already in req.generated; the plain
        # path's completion accounting takes over from here.
        eng.stats["decode_tokens"] += max(len(generated) - 1, 0)
        with recorder.phase("spec_fallback"):
            while True:
                for fin in eng.step():
                    if fin.req_id == req_id:
                        return fin
