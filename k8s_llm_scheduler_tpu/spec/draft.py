"""Draft runner: the small model that speculates K tokens ahead.

The draft is a full (small) Llama — train/distill.py's output is the
intended checkpoint — kept in a DENSE per-request KV buffer rather than the
paged cache: draft KV at distill scale is a few MB, rollback is pure host
bookkeeping (stale entries past the accepted prefix are never attended
because every step masks by position), and the buffer never contends with
the target's page pool.

The whole K-token proposal runs as ONE fused device program
(`_propose_impl`): a lax.scan of K single-token decode steps with sampling
and DFA transitions inside, so proposing costs one dispatch regardless of K
— per-token host round trips would eat the entire speculative win on a
tunneled TPU backend (the same economics that shaped the engine's fused
decision waves).

Grammar composition: each proposal step samples in K-space through the
SAME SparseDFATables the target uses (engine/engine._sample_sparse), so a
draft proposal is grammar-legal by construction.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_scheduler_tpu.engine.engine import _pick
from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import (
    Params,
    _dense,
    _logits,
    apply_rope,
    forward_prefill,
    rms_norm,
    rope_inv_freq,
)
from k8s_llm_scheduler_tpu.ops.attention import NEG_INF


def build_random_draft(
    draft_cfg: LlamaConfig, tokenizer_vocab: int, seed: int
) -> tuple[Params, LlamaConfig]:
    """Random-init a named draft config, widened to cover the tokenizer.

    THE single widening rule: a draft narrower than the tokenizer cannot
    propose every legal token, so its vocab pads up to the next multiple of
    128 (MXU lane width) at or above the tokenizer's. Serving
    (engine/local._attach_spec) and the bench A/B (bench.spec_ab) both
    build through here so they measure the same configuration."""
    if draft_cfg.vocab_size < tokenizer_vocab:
        draft_cfg = dataclasses.replace(
            draft_cfg, vocab_size=-(-tokenizer_vocab // 128) * 128
        )
    from k8s_llm_scheduler_tpu.models.llama import init_params

    return init_params(jax.random.PRNGKey(seed), draft_cfg), draft_cfg


def _draft_token_step(
    params: Params,
    cfg: LlamaConfig,
    tok,  # scalar int32 — the token being processed
    pos,  # scalar int32 — its absolute position (== dense-buffer index)
    k_buf,  # [L, cap, n_kv, hd] (carried)
    v_buf,
):
    """One draft decode step: write the token's K/V at `pos`, attend over
    buffer[0..pos], return (logits [V], k_buf, v_buf)."""
    hd = cfg.head_dim
    inv_freq = rope_inv_freq(cfg)
    x = params["embed"][tok]  # [D]

    def body(carry, xs):
        x, kb, vb = carry
        lp, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "d,dh->h").reshape(cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "d,dh->h").reshape(cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "d,dh->h").reshape(cfg.n_kv_heads, hd)
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        kb = kb.at[idx, pos].set(k.astype(kb.dtype))
        vb = vb.at[idx, pos].set(v.astype(vb.dtype))
        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
            cfg.n_kv_heads, cfg.q_per_kv, hd
        )
        keys = kb[idx].astype(jnp.float32)
        vals = vb[idx].astype(jnp.float32)
        logits = jnp.einsum("kgh,skh->kgs", qg, keys)
        mask = (jnp.arange(keys.shape[0]) <= pos)[None, None, :]
        logits = jnp.where(mask, logits, NEG_INF)
        w = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("kgs,skh->kgh", w, vals)
        attn = attn.reshape(cfg.n_heads * hd).astype(x.dtype)
        x = x + _dense(attn, lp["wo"], "h,hd->d")
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = _dense(h2, lp["w_gate"], "d,df->f")
        up = _dense(h2, lp["w_up"], "d,df->f")
        fused = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        x = x + _dense(fused, lp["w_down"], "f,fd->d")
        return (x, kb, vb), None

    (x, k_buf, v_buf), _ = jax.lax.scan(
        body, (x, k_buf, v_buf),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    return _logits(params, cfg, x), k_buf, v_buf


def _propose_impl(
    params: Params,
    cfg: LlamaConfig,  # static
    k_buf, v_buf,  # donated [L, cap, n_kv, hd]
    tok,    # scalar int32 — current last emitted token (KV not yet written)
    pos,    # scalar int32 — its absolute position
    state,  # scalar int32 — DFA state after `tok`
    sp_tokens, sp_next,  # sparse grammar tables (unused when unconstrained)
    pad_id,
    rng, temperature,
    K: int,              # static — proposal depth
    constrained: bool,   # static
    vocab_limit: int | None = None,  # static — see engine._sample_unconstrained
):
    """Propose K draft tokens in one fused program.

    Step i processes the (i-1)-th proposal (step 0 processes `tok`), writes
    its K/V into the dense buffer, samples proposal i from the grammar- (or
    pad-)masked logits, and advances the DFA state. Returns
    (tokens [K+1], states [K+1] — state AFTER each proposal, choice_idx [K]
    — the sampled index into the step's masked distribution, step_logits
    [K, X] — that masked distribution's logits (X = grammar K-width when
    constrained, vocab when not; the verifier's rejection sampler needs the
    draft's actual proposal distribution), k_buf, v_buf).

    The scan runs K+1 steps — the extra step processes the K-th proposal
    itself so the buffer holds valid KV through position pos+K. Without
    it, a fully-accepted round (a == K plus the bonus token) leaves the
    K-th proposal's buffer slot stale, and the next round's draft attends
    garbage from then on (measured: self-draft acceptance collapsed from
    1.0 to ~0.53). The extra step's sample is no longer discarded: it is
    the draft's GUESS at the round's bonus token — tokens[K] / states[K] —
    and the async pipeline (spec/decoder.py) anchors the AHEAD proposal
    for round n+1 on it while round n's verify is still in flight. When
    the verify's bonus token matches the guess, the pre-proposed block is
    exactly what a fresh propose would produce (greedy: bit-identical;
    sampling: a valid draw from the same proposal distribution), so the
    next round starts with zero draft latency on the critical path.
    """

    def step(carry, _):
        kb, vb, tok, pos, st, key = carry
        logits, kb, vb = _draft_token_step(params, cfg, tok, pos, kb, vb)
        key, sub = jax.random.split(key)
        if constrained:
            rows = sp_tokens[st]  # [Kw]
            gathered = logits[jnp.maximum(rows, 0)]
            masked = jnp.where(rows >= 0, gathered, NEG_INF)
            k_idx = _pick(masked[None, :], sub, temperature)[0]
            nxt_tok = rows[k_idx]
            nxt_st = sp_next[st, k_idx]
        else:
            V = logits.shape[-1]
            ids = jnp.arange(V)
            bad = ids == pad_id
            if vocab_limit is not None and vocab_limit < V:
                bad = bad | (ids >= vocab_limit)
            masked = jnp.where(bad, NEG_INF, logits)
            k_idx = _pick(masked[None, :], sub, temperature)[0]
            nxt_tok = k_idx
            nxt_st = st
        carry = (kb, vb, nxt_tok.astype(jnp.int32), pos + 1,
                 nxt_st.astype(jnp.int32), key)
        return carry, (nxt_tok.astype(jnp.int32), nxt_st.astype(jnp.int32),
                       k_idx.astype(jnp.int32), masked)

    (k_buf, v_buf, _, _, _, _), (toks, states, idxs, step_logits) = (
        jax.lax.scan(
            step, (k_buf, v_buf, tok, pos, state, rng), None, length=K + 1
        )
    )
    return toks, states, idxs[:K], step_logits[:K], k_buf, v_buf


def _prefill_impl(params, cfg, tokens, n, k_buf, v_buf):
    """Prefill the draft's dense buffer with the prompt's KV (bucketed
    [1, S] tokens; rows >= n are padding and get overwritten/masked)."""
    _, k_all, v_all = forward_prefill(
        params, cfg, tokens, jnp.asarray([n], dtype=jnp.int32),
        return_logits=False,
    )
    k_buf = jax.lax.dynamic_update_slice_in_dim(
        k_buf, k_all[:, 0].astype(k_buf.dtype), 0, axis=1
    )
    v_buf = jax.lax.dynamic_update_slice_in_dim(
        v_buf, v_all[:, 0].astype(v_buf.dtype), 0, axis=1
    )
    return k_buf, v_buf


class DraftRunner:
    """Per-request draft state over one small model.

    Single-owner like the engine; one request in flight at a time (the
    spec path serves `generate()` — the single-stream general-completion
    surface). `begin()` prefills the full prompt (shared prefix included —
    the draft holds its own dense KV, it does not read the target's
    buffers); `propose()` runs the fused K-step program; rollback is
    implicit (host position bookkeeping — see module doc).
    """

    CAP_ROUND = 256  # dense-buffer size bucket (bounds compile variants)

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        *,
        vocab_limit: int | None = None,
        prefill_round: int = 128,
    ) -> None:
        self.params = params
        self.cfg = cfg
        self.vocab_limit = vocab_limit
        self.prefill_round = int(prefill_round)
        self._k: jax.Array | None = None
        self._v: jax.Array | None = None
        self._cap = 0
        self._prefill = jax.jit(
            _prefill_impl, static_argnums=(1,), donate_argnums=(4, 5)
        )
        self._propose = jax.jit(
            functools.partial(_propose_impl, vocab_limit=vocab_limit),
            static_argnums=(1, 12, 13),
            donate_argnums=(2, 3),
        )

    @property
    def capacity(self) -> int:
        """Current dense-buffer capacity in tokens (0 before begin()) —
        the async pipeline checks AHEAD proposals against it instead of
        letting propose() raise mid-round."""
        return self._cap

    def begin(self, token_ids: list[int], pad_id: int, extra: int) -> None:
        """Start a request: allocate the dense buffer sized for
        `len(token_ids) + extra` tokens (bucketed) and prefill the prompt.

        `token_ids` is the FULL context (engine prefix tokens + request
        suffix); `extra` covers max_new + K + slack."""
        total = len(token_ids)
        cap = -(-(total + extra) // self.CAP_ROUND) * self.CAP_ROUND
        shape = (self.cfg.n_layers, cap, self.cfg.n_kv_heads, self.cfg.head_dim)
        if self._k is None or self._cap != cap:
            self._cap = cap
            self._k = jnp.zeros(shape, dtype=self.cfg.dtype)
            self._v = jnp.zeros(shape, dtype=self.cfg.dtype)
        bucket = -(-total // self.prefill_round) * self.prefill_round
        assert bucket <= cap, (bucket, cap)
        tokens = np.full((1, bucket), pad_id, dtype=np.int32)
        tokens[0, :total] = token_ids
        self._k, self._v = self._prefill(
            self.params, self.cfg, jnp.asarray(tokens), total, self._k, self._v
        )

    def propose(
        self, tok, pos: int, state,
        sp_tokens, sp_next, pad_id: int,
        rng, temperature: float, k: int, constrained: bool,
    ):
        """Fused K-token proposal from (tok @ pos, DFA state). Returns the
        device arrays from _propose_impl (no host sync — the verifier
        consumes them directly). `tok`/`state` may be host ints OR device
        scalars: the async pipeline's AHEAD propose anchors on the
        previous proposal's device-resident guess (toks[K]/states[K])
        without ever fetching it. `pos` stays a host int — the overflow
        check below is host bookkeeping."""
        if self._k is None:
            raise RuntimeError("DraftRunner.begin() not called")
        if pos + k + 1 > self._cap:  # K+1 steps write pos..pos+K
            raise RuntimeError(
                f"draft buffer overflow: pos {pos} + K+1 {k + 1} > cap {self._cap}"
            )
        toks, states, idxs, step_logits, self._k, self._v = self._propose(
            self.params, self.cfg, self._k, self._v,
            jnp.int32(tok), jnp.int32(pos), jnp.int32(state),
            sp_tokens, sp_next, jnp.int32(pad_id),
            rng, jnp.float32(temperature), k, constrained,
        )
        return toks, states, idxs, step_logits
