"""Hidden-transfer draft-free speculation: propose INSIDE the verify.

*Hidden Transfer* (PAPERS.md) replaces the second model entirely: the
target's own final-layer hidden state at the acceptance point is linearly
"transferred" to pseudo hidden states for the next K future positions
(models/llama.init_hidden_transfer — per-offset residual matrices trained
by train/hidden.py), and the model's own LM head turns each into a
proposal distribution. The consequence for the async pipeline
(spec/decoder.py) is structural: proposing costs ZERO extra dispatches —
`_hidden_verify_impl` is ONE device program per round that

1. scores the current block exactly as the draft arm's verify does
   (spec/verify._forward_verify_block — same cascade, same KV scatter,
   same on-device acceptance, so greedy output is token-identical to
   plain decode by the same argument);
2. gathers the final-layer hidden state at the acceptance index `a` (a
   device-side gather — whichever prefix survives, the proposal chain
   grows from the right context);
3. chains K grammar-masked proposals from the transfer heads: each step
   masks through the SAME tables the engine decodes with (dense
   transition-table row gather when the grammar exports one — the fused
   runtime's table — else sparse K-space), advances the DFA state, and
   records the masked proposal logits the NEXT round's rejection sampler
   needs.

The proposals ride back device-resident: round n+1's block is assembled
from round n's outputs without a host round trip, so the only per-round
sync is the accept fetch — the draft stream has collapsed INTO the verify
stream. A `W=1` bootstrap geometry (block = [first_token], K=0 drafts)
starts each request and produces the first proposal block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from k8s_llm_scheduler_tpu.engine.engine import _pick
from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import (
    Params,
    _logits,
    hidden_transfer_hidden,
)
from k8s_llm_scheduler_tpu.ops.attention import NEG_INF
from k8s_llm_scheduler_tpu.spec.verify import (
    _accept_block,
    _forward_verify_block,
    _masked_target,
)


def _hidden_verify_impl(
    params: Params,
    cfg: LlamaConfig,  # static
    ht: Params,        # {"transfer": [K, D, D]} hidden-transfer head
    blk_tok,      # [W] — [t_cur, g_1..g_{W-1}] (W=1 on the bootstrap round)
    positions,    # [W]
    prefix_k, prefix_v, prefix_len,
    k_cache, v_cache,  # donated
    page_table, own_len, page_ids, offs,
    mask_states,   # [W] — DFA state governing the token AFTER blk_tok[i]
    choice_idx,    # [W-1] — proposal's masked-space index (rejection path)
    guess_logits,  # [W-1, X] — previous round's masked proposal logits
    sp_tokens, sp_next,
    dense_next,    # [S, V] dense transition table (grammar == "dense")
    pad_id,
    rng, temperature,
    grammar: str,               # static — verify.GRAMMAR_MODES
    greedy: bool,               # static
    n_guess: int,               # static — K proposals for the NEXT round
    vocab_limit: int | None = None,  # static
    prefix_impl=None,           # static
):
    """Verify the current block AND propose the next one, one program.

    Returns (a, t_next, st_next,
             g_toks [n_guess], g_states [n_guess], g_idx [n_guess],
             g_logits [n_guess, X], k_cache, v_cache).
    g_states[h] is the DFA state AFTER guess h; g_logits are the masked
    proposal logits (the q distributions for the next round's rejection
    sampler); X matches the accept path's space (K-width under "sparse",
    vocab otherwise)."""
    logits_all, x_all, k_cache, v_cache = _forward_verify_block(
        params, cfg, blk_tok, positions, prefix_k, prefix_v, prefix_len,
        k_cache, v_cache, page_table, own_len, page_ids, offs,
        prefix_impl=prefix_impl,
    )
    masked, idx_to_tok = _masked_target(
        logits_all, mask_states, sp_tokens, sp_next, dense_next,
        pad_id, grammar, vocab_limit,
    )
    rng_acc, rng_g = jax.random.split(rng)
    a, t_next, st_next = _accept_block(
        masked, idx_to_tok, blk_tok[1:], choice_idx, guess_logits,
        rng_acc, temperature, grammar, greedy,
        sp_tokens=sp_tokens, mask_states=mask_states,
    )

    # ---- propose the next block from the hidden state at the acceptance
    # point: x_all[a] predicted t_next; head h predicts the h+1-th token
    # after it. The chain is sequential in the DFA state (h's legality
    # depends on h-1's guess) but every step is pure gathers + one LM-head
    # matmul — no model call.
    x_a = x_all[a]  # [D]
    st = st_next.astype(jnp.int32)
    keys = jax.random.split(rng_g, max(n_guess, 1))
    g_toks, g_states, g_idx, g_logits = [], [], [], []
    for h in range(n_guess):
        xh = hidden_transfer_hidden(ht, x_a, h)
        lg = _logits(params, cfg, xh)  # [V] f32
        if grammar == "dense":
            row = dense_next[st]  # [V]
            m = jnp.where(row >= 0, lg, NEG_INF)
            k_idx = _pick(m[None, :], keys[h], temperature)[0]
            tok = k_idx
            nxt = row[k_idx]
        elif grammar == "sparse":
            rows = sp_tokens[st]  # [Kw]
            gathered = lg[jnp.maximum(rows, 0)]
            m = jnp.where(rows >= 0, gathered, NEG_INF)
            k_idx = _pick(m[None, :], keys[h], temperature)[0]
            tok = rows[k_idx]
            nxt = sp_next[st, k_idx]
        else:
            V = lg.shape[-1]
            ids = jnp.arange(V)
            bad = ids == pad_id
            if vocab_limit is not None and vocab_limit < V:
                bad = bad | (ids >= vocab_limit)
            m = jnp.where(bad, NEG_INF, lg)
            k_idx = _pick(m[None, :], keys[h], temperature)[0]
            tok = k_idx
            nxt = st
        g_toks.append(tok.astype(jnp.int32))
        g_states.append(nxt.astype(jnp.int32))
        g_idx.append(k_idx.astype(jnp.int32))
        g_logits.append(m)
        st = nxt.astype(jnp.int32)

    return (
        a.astype(jnp.int32),
        t_next.astype(jnp.int32),
        st_next.astype(jnp.int32),
        jnp.stack(g_toks),
        jnp.stack(g_states),
        jnp.stack(g_idx),
        jnp.stack(g_logits),
        k_cache,
        v_cache,
    )
