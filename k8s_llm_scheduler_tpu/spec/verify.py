"""Target-side verification: score K draft tokens in ONE forward, accept.

`_verify_impl` is one device program per (W, grammar-shape) geometry that

1. runs the target over the W-token block [t_cur, d_1..d_K] against
   (shared dense prefix | own paged KV | causal-within-block) — the same
   three-part log-sum-exp cascade the plain chunked-decode path uses
   (models/llama.forward_decode_buffered), so greedy speculative output is
   token-identical to plain decode;
2. scatters the block's K/V into the slot's cache pages as it goes (the
   accepted prefix is then already resident; the rejected tail unwinds via
   kv_cache.truncate — stale page contents are never attended because every
   reader masks by valid length);
3. applies the SAME grammar masking as the constrained decoder
   (SparseDFATables in K-space) to every position's target distribution —
   verification can never accept or emit a grammar-illegal token;
4. accepts on device: greedy mode takes the longest draft prefix matching
   the target argmax and emits the target's token at the first divergence
   (so output == plain greedy decode by construction); sampling mode runs
   standard speculative rejection sampling (accept d_i with prob
   min(1, p_i/q_i); on rejection resample from normalize(max(p-q, 0))),
   which preserves the target distribution exactly.

Returns (accept_count, next_token, next_state, k_cache, v_cache) — one
fetch per round, everything else stays on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import (
    Params,
    _dense,
    _logits,
    _mlp,
    apply_rope,
    rms_norm,
    rope_inv_freq,
)
from k8s_llm_scheduler_tpu.ops.attention import (
    NEG_INF,
    attend_part,
    merge_attention_parts,
    prefix_attend_parts,
)


def _forward_verify_block(
    params: Params,
    cfg: LlamaConfig,
    blk_tok,      # [W] int32 — [t_cur, d_1..d_K]
    positions,    # [W] absolute positions
    prefix_k, prefix_v,  # [L, Sp, n_kv, hd] shared dense prefix
    prefix_len,   # scalar int32
    k_cache, v_cache,    # [L, num_pages, ps, n_kv, hd] (donated by caller)
    page_table,   # [1, P] — the slot's own-page table row
    own_len,      # scalar int32 — valid own tokens in pages (< positions[0])
    page_ids, offs,      # [W] scatter destinations for the block's KV
    prefix_impl=None,    # static
):
    """Target forward over the block; returns (logits [W, V] f32, caches)."""
    W = blk_tok.shape[0]
    hd = cfg.head_dim
    ps = k_cache.shape[2]
    P = page_table.shape[1]
    inv_freq = rope_inv_freq(cfg)
    pos_b = positions[None, :]  # [1, W]

    x = params["embed"][blk_tok][None]  # [1, W, D]
    own_mask = (jnp.arange(P * ps)[None, :] < own_len)[:, None, None, None, :]
    j = jnp.arange(W)
    blk_mask = (j[:, None] >= j[None, :])[None, None, None, :, :]

    def body(carry, xs):
        x, kc, vc = carry
        lp, pk, pv, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bsd,dh->bsh").reshape(1, W, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bsd,dh->bsh").reshape(1, W, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bsd,dh->bsh").reshape(1, W, cfg.n_kv_heads, hd)
        q = apply_rope(q, pos_b, inv_freq)
        k = apply_rope(k, pos_b, inv_freq)
        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
            1, W, cfg.n_kv_heads, cfg.q_per_kv, hd
        )
        k_own = kc[idx][page_table].reshape(1, P * ps, cfg.n_kv_heads, hd)
        v_own = vc[idx][page_table].reshape(1, P * ps, cfg.n_kv_heads, hd)
        parts = [
            prefix_attend_parts(q, qg, pk, pv, prefix_len, impl=prefix_impl),
            attend_part(qg, k_own, v_own, own_mask, "bqkgh,bskh->bkgqs"),
            attend_part(qg, k, v, blk_mask, "bqkgh,bskh->bkgqs"),
        ]
        attn = merge_attention_parts(parts)  # [1, n_kv, g, W, hd]
        attn = jnp.moveaxis(attn, 3, 1).reshape(1, W, cfg.n_heads * hd)
        x = x + _dense(attn.astype(x.dtype), lp["wo"], "bsh,hd->bsd")
        x = x + _mlp(lp, cfg, x)
        kc = kc.at[idx, page_ids, offs].set(k[0].astype(kc.dtype))
        vc = vc.at[idx, page_ids, offs].set(v[0].astype(vc.dtype))
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        body, (x, k_cache, v_cache),
        (params["layers"], prefix_k, prefix_v, jnp.arange(cfg.n_layers)),
    )
    return _logits(params, cfg, x[0]), k_cache, v_cache


def _verify_impl(
    params: Params,
    cfg: LlamaConfig,  # static
    blk_tok,      # [W] — [t_cur, d_1..d_K]
    positions,    # [W]
    prefix_k, prefix_v, prefix_len,
    k_cache, v_cache,  # donated
    page_table, own_len, page_ids, offs,
    mask_states,   # [W] — DFA state governing the token AFTER blk_tok[i]
    choice_idx,    # [K] — draft's sampled index per step (rejection path)
    draft_logits,  # [K, X] — draft's masked proposal logits (rejection path)
    sp_tokens, sp_next,
    pad_id,
    rng, temperature,
    constrained: bool,          # static
    greedy: bool,               # static — temperature == 0 fast path
    vocab_limit: int | None = None,  # static
    prefix_impl=None,           # static
):
    """Score + accept in one program. See module doc for the contract."""
    W = blk_tok.shape[0]
    K = W - 1
    logits_all, k_cache, v_cache = _forward_verify_block(
        params, cfg, blk_tok, positions, prefix_k, prefix_v, prefix_len,
        k_cache, v_cache, page_table, own_len, page_ids, offs,
        prefix_impl=prefix_impl,
    )

    if constrained:
        rows_all = sp_tokens[mask_states]          # [W, Kw]
        next_all = sp_next[mask_states]            # [W, Kw]
        gathered = jnp.take_along_axis(
            logits_all, jnp.maximum(rows_all, 0), axis=1
        )
        masked = jnp.where(rows_all >= 0, gathered, NEG_INF)  # [W, Kw]

        def idx_to_tok(i, k_idx):
            return rows_all[i, k_idx], next_all[i, k_idx]
    else:
        V = logits_all.shape[-1]
        ids = jnp.arange(V)[None, :]
        bad = ids == pad_id
        if vocab_limit is not None and vocab_limit < V:
            bad = bad | (ids >= vocab_limit)
        masked = jnp.where(bad, NEG_INF, logits_all)  # [W, V]

        def idx_to_tok(i, k_idx):
            return k_idx, mask_states[i]

    drafts = blk_tok[1:]  # [K]
    if greedy:
        tgt_k = jnp.argmax(masked, axis=-1)  # [W]
        if constrained:
            tgt_tok = jnp.take_along_axis(rows_all, tgt_k[:, None], 1)[:, 0]
        else:
            tgt_tok = tgt_k
        match = (tgt_tok[:K] == drafts).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(match)) if K > 0 else jnp.int32(0)
        t_next, st_next = idx_to_tok(a, tgt_k[a])
    else:
        if not constrained:
            # Align vocab widths: the draft's padded vocab may differ from
            # the target's (widened to a 128 multiple, or simply a
            # different config). Both maskings confine all legal mass to
            # [0, tokenizer_vocab), which is <= both widths, so slicing to
            # the common width drops only NEG_INF/zero-probability tail.
            v_common = min(masked.shape[-1], draft_logits.shape[-1])
            masked = masked[:, :v_common]
            draft_logits = draft_logits[:, :v_common]
        t = jnp.maximum(temperature, 1e-6)
        p = jax.nn.softmax(masked / t, axis=-1)        # [W, X]
        rng_u, rng_s = jax.random.split(rng)
        if K > 0:
            q = jax.nn.softmax(draft_logits / t, axis=-1)  # [K, X]
            p_tok = jnp.take_along_axis(p[:K], choice_idx[:, None], 1)[:, 0]
            q_tok = jnp.take_along_axis(q, choice_idx[:, None], 1)[:, 0]
            u = jax.random.uniform(rng_u, (K,))
            acc = (u * q_tok < p_tok).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(acc))
            # Rejection at position a: resample from the residual
            # normalize(max(p - q, 0)) — the correction that makes the
            # emitted marginal exactly the target's. All-accepted (a == K):
            # the bonus token samples from the target directly.
            p_a = p[a]
            q_a = q[jnp.minimum(a, K - 1)]
            resid = jnp.clip(p_a - q_a, 0.0, None)
            resid = jnp.where(jnp.sum(resid) > 0, resid, p_a)
            dist = jnp.where(a < K, resid, p_a)
        else:
            a = jnp.int32(0)
            dist = p[0]
        k_choice = jax.random.categorical(rng_s, jnp.log(dist + 1e-30))
        t_next, st_next = idx_to_tok(a, k_choice)

    return (
        a.astype(jnp.int32),
        t_next.astype(jnp.int32),
        st_next.astype(jnp.int32),
        k_cache,
        v_cache,
    )
