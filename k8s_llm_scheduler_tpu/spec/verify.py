"""Target-side verification: score K draft tokens in ONE forward, accept.

`_verify_impl` is one device program per (W, grammar-shape) geometry that

1. runs the target over the W-token block [t_cur, d_1..d_K] against
   (shared dense prefix | own paged KV | causal-within-block) — the same
   three-part log-sum-exp cascade the plain chunked-decode path uses
   (models/llama.forward_decode_buffered), so greedy speculative output is
   token-identical to plain decode;
2. scatters the block's K/V into the slot's cache pages as it goes (the
   accepted prefix is then already resident; the rejected tail unwinds via
   kv_cache.truncate — stale page contents are never attended because every
   reader masks by valid length);
3. applies the SAME grammar as the constrained decoder to every position's
   target distribution — verification can never accept or emit a
   grammar-illegal token. Greedy constrained verification reads the DENSE
   transition table when the engine's grammar exports one
   (engine/fused/tables.py — the fused runtime's table, shared, not a
   twin): the allowed mask is one row gather `dense_next[state] >= 0` and
   the transition one element gather, exactly the discipline the fused
   while_loop uses. Sampling-mode and cap-exceeded grammars keep the
   sparse K-space tables (the rejection sampler's proposal distributions
   live in K-space);
4. accepts on device: greedy mode takes the longest draft prefix matching
   the target argmax and emits the target's token at the first divergence
   (so output == plain greedy decode by construction); sampling mode runs
   standard speculative rejection sampling (accept d_i with prob
   min(1, p_i/q_i); on rejection resample from normalize(max(p-q, 0))),
   which preserves the target distribution exactly.

Returns (accept_count, next_token, next_state, k_cache, v_cache) — one
fetch per round, everything else stays on device. The forward also
returns the block's final-layer hidden states so the draft-free
hidden-transfer arm (spec/hidden.py) can grow its next proposal block
inside the same program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from k8s_llm_scheduler_tpu.models.configs import LlamaConfig
from k8s_llm_scheduler_tpu.models.llama import (
    Params,
    _dense,
    _logits,
    _mlp,
    apply_rope,
    rms_norm,
    rope_inv_freq,
)
from k8s_llm_scheduler_tpu.ops.attention import (
    NEG_INF,
    attend_part,
    merge_attention_parts,
    prefix_attend_parts,
)

# Grammar implementations a verify program can compile against. "dense"
# is greedy-only (the rejection sampler needs K-space proposal
# distributions); the decoder picks per engine state — see
# SpeculativeDecoder._grammar_mode.
GRAMMAR_MODES = ("none", "sparse", "dense")


def _forward_verify_block(
    params: Params,
    cfg: LlamaConfig,
    blk_tok,      # [W] int32 — [t_cur, d_1..d_K]
    positions,    # [W] absolute positions
    prefix_k, prefix_v,  # [L, Sp, n_kv, hd] shared dense prefix
    prefix_len,   # scalar int32
    k_cache, v_cache,    # [L, num_pages, ps, n_kv, hd] (donated by caller)
    page_table,   # [1, P] — the slot's own-page table row
    own_len,      # scalar int32 — valid own tokens in pages (< positions[0])
    page_ids, offs,      # [W] scatter destinations for the block's KV
    prefix_impl=None,    # static
):
    """Target forward over the block; returns
    (logits [W, V] f32, hidden [W, D] — final-layer pre-norm residual
    stream, caches). The hidden states feed the hidden-transfer arm's
    on-device proposal chain (spec/hidden.py)."""
    W = blk_tok.shape[0]
    hd = cfg.head_dim
    ps = k_cache.shape[2]
    P = page_table.shape[1]
    inv_freq = rope_inv_freq(cfg)
    pos_b = positions[None, :]  # [1, W]

    x = params["embed"][blk_tok][None]  # [1, W, D]
    own_mask = (jnp.arange(P * ps)[None, :] < own_len)[:, None, None, None, :]
    j = jnp.arange(W)
    blk_mask = (j[:, None] >= j[None, :])[None, None, None, :, :]

    def body(carry, xs):
        x, kc, vc = carry
        lp, pk, pv, idx = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = _dense(h, lp["wq"], "bsd,dh->bsh").reshape(1, W, cfg.n_heads, hd)
        k = _dense(h, lp["wk"], "bsd,dh->bsh").reshape(1, W, cfg.n_kv_heads, hd)
        v = _dense(h, lp["wv"], "bsd,dh->bsh").reshape(1, W, cfg.n_kv_heads, hd)
        q = apply_rope(q, pos_b, inv_freq)
        k = apply_rope(k, pos_b, inv_freq)
        qg = (q.astype(jnp.float32) * hd**-0.5).reshape(
            1, W, cfg.n_kv_heads, cfg.q_per_kv, hd
        )
        k_own = kc[idx][page_table].reshape(1, P * ps, cfg.n_kv_heads, hd)
        v_own = vc[idx][page_table].reshape(1, P * ps, cfg.n_kv_heads, hd)
        parts = [
            prefix_attend_parts(q, qg, pk, pv, prefix_len, impl=prefix_impl),
            attend_part(qg, k_own, v_own, own_mask, "bqkgh,bskh->bkgqs"),
            attend_part(qg, k, v, blk_mask, "bqkgh,bskh->bkgqs"),
        ]
        attn = merge_attention_parts(parts)  # [1, n_kv, g, W, hd]
        attn = jnp.moveaxis(attn, 3, 1).reshape(1, W, cfg.n_heads * hd)
        x = x + _dense(attn.astype(x.dtype), lp["wo"], "bsh,hd->bsd")
        x = x + _mlp(lp, cfg, x)
        kc = kc.at[idx, page_ids, offs].set(k[0].astype(kc.dtype))
        vc = vc.at[idx, page_ids, offs].set(v[0].astype(vc.dtype))
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        body, (x, k_cache, v_cache),
        (params["layers"], prefix_k, prefix_v, jnp.arange(cfg.n_layers)),
    )
    return _logits(params, cfg, x[0]), x[0], k_cache, v_cache


def _masked_target(
    logits_all,   # [W, V] f32
    mask_states,  # [W] — DFA state governing the token AFTER blk_tok[i]
    sp_tokens, sp_next,  # sparse tables (grammar == "sparse")
    dense_next,   # [S, V] dense table (grammar == "dense")
    pad_id,
    grammar: str,              # static — one of GRAMMAR_MODES
    vocab_limit: int | None,   # static
):
    """Grammar-mask every position's target distribution.

    Returns (masked [W, X], idx_to_tok) where idx_to_tok(i, k) maps a
    selection index in the masked space back to (token id, next DFA
    state). X = vocab for "dense"/"none" (token id == index), grammar
    K-width for "sparse"."""
    if grammar == "dense":
        rows_all = dense_next[mask_states]  # [W, V]
        masked = jnp.where(rows_all >= 0, logits_all, NEG_INF)

        def idx_to_tok(i, k_idx):
            return k_idx, rows_all[i, k_idx]
    elif grammar == "sparse":
        tok_rows = sp_tokens[mask_states]          # [W, Kw]
        next_rows = sp_next[mask_states]           # [W, Kw]
        gathered = jnp.take_along_axis(
            logits_all, jnp.maximum(tok_rows, 0), axis=1
        )
        masked = jnp.where(tok_rows >= 0, gathered, NEG_INF)  # [W, Kw]

        def idx_to_tok(i, k_idx):
            return tok_rows[i, k_idx], next_rows[i, k_idx]
    else:
        V = logits_all.shape[-1]
        ids = jnp.arange(V)[None, :]
        bad = ids == pad_id
        if vocab_limit is not None and vocab_limit < V:
            bad = bad | (ids >= vocab_limit)
        masked = jnp.where(bad, NEG_INF, logits_all)  # [W, V]

        def idx_to_tok(i, k_idx):
            return k_idx, mask_states[i]

    return masked, idx_to_tok


def _accept_block(
    masked,        # [W, X] grammar-masked target logits
    idx_to_tok,    # from _masked_target
    drafts,        # [K] proposed token ids (blk_tok[1:])
    choice_idx,    # [K] draft's sampled index per step (rejection path)
    draft_logits,  # [K, X'] draft's masked proposal logits (rejection path)
    rng, temperature,
    grammar: str,   # static
    greedy: bool,   # static — temperature == 0 fast path
    sp_tokens=None, mask_states=None,  # sparse token rows (greedy map-back)
):
    """On-device acceptance over a masked block. Returns
    (a — accepted prefix length, t_next, st_next).

    Greedy: longest draft prefix matching the target argmax, target token
    at the divergence — output == plain greedy decode by construction.
    Sampling: standard speculative rejection sampling in the draft's
    proposal space (K-space under a sparse grammar, token space
    otherwise); preserves the target distribution exactly."""
    W = masked.shape[0]
    K = W - 1
    if greedy:
        tgt_k = jnp.argmax(masked, axis=-1)  # [W]
        if grammar == "sparse":
            rows_all = sp_tokens[mask_states]
            tgt_tok = jnp.take_along_axis(rows_all, tgt_k[:, None], 1)[:, 0]
        else:
            tgt_tok = tgt_k
        match = (tgt_tok[:K] == drafts).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(match)) if K > 0 else jnp.int32(0)
        t_next, st_next = idx_to_tok(a, tgt_k[a])
    else:
        if grammar != "sparse" and K > 0:
            # Align vocab widths: the draft's padded vocab may differ from
            # the target's (widened to a 128 multiple, or simply a
            # different config). Both maskings confine all legal mass to
            # [0, tokenizer_vocab), which is <= both widths, so slicing to
            # the common width drops only NEG_INF/zero-probability tail.
            # K == 0 (a bootstrap block with no proposals) must NOT align:
            # its draft_logits is a [0, 1] placeholder and slicing the
            # target to width 1 would leave only the pad column.
            v_common = min(masked.shape[-1], draft_logits.shape[-1])
            masked = masked[:, :v_common]
            draft_logits = draft_logits[:, :v_common]
        t = jnp.maximum(temperature, 1e-6)
        p = jax.nn.softmax(masked / t, axis=-1)        # [W, X]
        rng_u, rng_s = jax.random.split(rng)
        if K > 0:
            q = jax.nn.softmax(draft_logits / t, axis=-1)  # [K, X]
            p_tok = jnp.take_along_axis(p[:K], choice_idx[:, None], 1)[:, 0]
            q_tok = jnp.take_along_axis(q, choice_idx[:, None], 1)[:, 0]
            u = jax.random.uniform(rng_u, (K,))
            acc = (u * q_tok < p_tok).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(acc))
            # Rejection at position a: resample from the residual
            # normalize(max(p - q, 0)) — the correction that makes the
            # emitted marginal exactly the target's. All-accepted (a == K):
            # the bonus token samples from the target directly.
            p_a = p[a]
            q_a = q[jnp.minimum(a, K - 1)]
            resid = jnp.clip(p_a - q_a, 0.0, None)
            resid = jnp.where(jnp.sum(resid) > 0, resid, p_a)
            dist = jnp.where(a < K, resid, p_a)
        else:
            a = jnp.int32(0)
            dist = p[0]
        k_choice = jax.random.categorical(rng_s, jnp.log(dist + 1e-30))
        t_next, st_next = idx_to_tok(a, k_choice)
    return a, t_next, st_next


def _verify_impl(
    params: Params,
    cfg: LlamaConfig,  # static
    blk_tok,      # [W] — [t_cur, d_1..d_K]
    positions,    # [W]
    prefix_k, prefix_v, prefix_len,
    k_cache, v_cache,  # donated
    page_table, own_len, page_ids, offs,
    mask_states,   # [W] — DFA state governing the token AFTER blk_tok[i]
    choice_idx,    # [K] — draft's sampled index per step (rejection path)
    draft_logits,  # [K, X] — draft's masked proposal logits (rejection path)
    sp_tokens, sp_next,
    dense_next,    # [S, V] dense transition table (grammar == "dense")
    pad_id,
    rng, temperature,
    grammar: str,               # static — one of GRAMMAR_MODES
    greedy: bool,               # static — temperature == 0 fast path
    vocab_limit: int | None = None,  # static
    prefix_impl=None,           # static
):
    """Score + accept in one program. See module doc for the contract."""
    logits_all, _x, k_cache, v_cache = _forward_verify_block(
        params, cfg, blk_tok, positions, prefix_k, prefix_v, prefix_len,
        k_cache, v_cache, page_table, own_len, page_ids, offs,
        prefix_impl=prefix_impl,
    )
    masked, idx_to_tok = _masked_target(
        logits_all, mask_states, sp_tokens, sp_next, dense_next,
        pad_id, grammar, vocab_limit,
    )
    a, t_next, st_next = _accept_block(
        masked, idx_to_tok, blk_tok[1:], choice_idx, draft_logits,
        rng, temperature, grammar, greedy,
        sp_tokens=sp_tokens, mask_states=mask_states,
    )
    return (
        a.astype(jnp.int32),
        t_next.astype(jnp.int32),
        st_next.astype(jnp.int32),
        k_cache,
        v_cache,
    )
