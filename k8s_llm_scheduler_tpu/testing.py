"""Fixture generators shared by tests and bench.py.

The reference ships 3 nginx fixture pods with graduated requests
(reference ai-test-pods.yaml:1-44: 100m/128Mi, 250m/256Mi, 500m/512Mi)
targeting schedulerName ai-llama-scheduler. `fixture_pods()` reproduces that
workload; `synthetic_cluster`/`pod_burst` generate the BASELINE stress shapes
(64/256-node clusters, 1000-pod bursts).

Also home of `async_deadline()` — the Python-3.10-compatible stand-in for
the 3.11+ ``asyncio.timeout`` context manager that every async test's
watchdog goes through (the package floor is >=3.10; tools/graftlint's
py310 rule family keeps direct 3.11+-only calls from creeping back in) —
and of `LockOrderSanitizer`, the runtime half of the concurrency
discipline graftlint checks statically: it wraps `threading.Lock`
creation for a test's duration, records the cross-thread lock
ACQUISITION-ORDER graph, and flags order cycles (latent ABBA deadlocks
that a run only hits under exact interleaving) and locks held across an
event-loop hop (the loop ran other callbacks while a threading lock was
held). Opt in per test via the `lock_sanitizer` fixture (tests/conftest),
or across the whole fast tier with GRAFT_LOCK_SANITIZER=1.
"""

from __future__ import annotations

import asyncio
import threading

from k8s_llm_scheduler_tpu.cluster.fake import FakeCluster, FakeNode
from k8s_llm_scheduler_tpu.cluster.interface import RawPod

SCHEDULER_NAME = "ai-llama-scheduler"


class _Py310Deadline:
    """Minimal backport of the 3.11 timeout context manager: arm a timer
    that cancels the CURRENT task; translate the resulting CancelledError
    into TimeoutError iff this deadline (not an outer cancel) fired."""

    def __init__(self, seconds: float) -> None:
        self._seconds = seconds
        self._fired = False
        self._handle = None
        self._task = None

    async def __aenter__(self) -> "_Py310Deadline":
        self._task = asyncio.current_task()
        loop = asyncio.get_running_loop()
        self._handle = loop.call_later(self._seconds, self._on_timeout)
        return self

    def _on_timeout(self) -> None:
        self._fired = True
        if self._task is not None:
            self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if self._handle is not None:
            self._handle.cancel()
        if self._fired:
            if exc_type is asyncio.CancelledError:
                raise TimeoutError(
                    f"deadline of {self._seconds}s expired"
                ) from exc
            if exc_type is None:
                # Timer fired in the gap between the block's last await and
                # exit: the task.cancel() is still pending and would escape
                # as a bare CancelledError at the caller's NEXT await.
                # Absorb it at a checkpoint here and report the expiry
                # (3.11's native timeout resolves this boundary the same
                # way, via Task.uncancel bookkeeping).
                try:
                    await asyncio.sleep(0)
                except asyncio.CancelledError:
                    raise TimeoutError(
                        f"deadline of {self._seconds}s expired"
                    ) from None
        return False


def async_deadline(seconds: float):
    """``async with async_deadline(30): ...`` — bound an async block's wall
    time. Python 3.11+'s native scoped timeout when available (it handles
    nested-cancellation bookkeeping via Task.uncancel); a call_later-based
    shim with the same raise-TimeoutError contract on 3.10."""
    native = getattr(asyncio, "timeout", None)  # 3.11+
    if native is not None:
        return native(seconds)
    return _Py310Deadline(seconds)


# ------------------------------------------------------------------------
# Runtime lock-order sanitizer
# ------------------------------------------------------------------------


class LockOrderViolation(AssertionError):
    """A lock-discipline violation observed at runtime (order cycle or a
    lock held across an event-loop hop)."""


class _SanitizedLock:
    """Drop-in wrapper around a real `_thread.lock` that reports acquire/
    release events to its sanitizer. Identity for the order graph is the
    CREATION SITE (file:line), not the instance — two instances of the
    same class's `self._lock` are one graph node, so an ABBA cycle between
    two objects of the same class is still a cycle."""

    __slots__ = ("_real", "_san", "site", "_holder")

    def __init__(self, real, sanitizer: "LockOrderSanitizer", site: str) -> None:
        self._real = real
        self._san = sanitizer
        self.site = site
        # ident of the thread currently holding this lock (None when free
        # or released cross-thread) — lets the sanitizer purge hand-off
        # residue from the acquirer's held stack (see _note_acquire)
        self._holder: int | None = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            self._holder = threading.get_ident()
            self._san._note_acquire(self)
        return got

    def release(self) -> None:
        self._san._note_release(self)
        self._holder = None
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:  # threading internals call this
        self._real._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.site} {self._real!r}>"


class LockOrderSanitizer:
    """Wrap `threading.Lock` creation; record the acquisition graph; fail
    on cycles and on locks held across an event-loop hop.

    Checks (both are the runtime twin of a graftlint rule):

    - **order cycle**: edge A->B is recorded when a thread acquires B
      while holding A. A cycle in that graph is a latent deadlock even if
      this run's interleaving never wedged — exactly the class the test
      suite can only catch probabilistically.
    - **event-loop hop**: acquiring a threading lock on a loop thread and
      holding it across a loop iteration (detected via a patched
      `asyncio.events.Handle._run` tick counter: if the loop ran any
      OTHER callback between acquire and release, the holder suspended
      mid-critical-section — the runtime shape of `lock-across-await`).

    Violations are recorded, not raised at the fault site (raising inside
    arbitrary third-party acquire paths corrupts the code under test);
    `assert_clean()` — which the pytest fixture calls at teardown —
    raises LockOrderViolation with every observation.

    Scope: only locks CREATED while installed are tracked (the fixture
    installs before the test body, so objects the test builds are
    covered); `threading.RLock` is left alone (logging and interpreter
    internals). Use as a context manager, or install()/uninstall()."""

    def __init__(self) -> None:
        self._orig_lock = None
        self._orig_handle_run = None
        self._meta = threading.Lock()  # guards graph/violations (real lock)
        self._tls = threading.local()
        self.edges: dict[str, set[str]] = {}
        self.edge_where: dict[tuple[str, str], str] = {}
        self.violations: list[str] = []
        self.locks_created = 0

    # ------------------------------------------------------------ lifecycle
    def install(self) -> "LockOrderSanitizer":
        if self._orig_lock is not None:
            raise RuntimeError("sanitizer already installed")
        self._orig_lock = threading.Lock
        sanitizer = self

        def make_lock():
            sanitizer.locks_created += 1
            return _SanitizedLock(
                sanitizer._orig_lock(), sanitizer, sanitizer._creation_site()
            )

        threading.Lock = make_lock  # type: ignore[assignment]

        # Event-loop tick counter: every callback the loop runs bumps the
        # per-thread counter, so "held across a hop" is a counter delta.
        handle_cls = asyncio.events.Handle
        self._orig_handle_run = handle_cls._run
        orig_run = self._orig_handle_run

        def counting_run(handle_self):
            tls = sanitizer._tls
            tls.loop_ticks = getattr(tls, "loop_ticks", 0) + 1
            return orig_run(handle_self)

        handle_cls._run = counting_run  # type: ignore[assignment]
        return self

    def uninstall(self) -> None:
        if self._orig_lock is None:
            return
        threading.Lock = self._orig_lock  # type: ignore[assignment]
        asyncio.events.Handle._run = self._orig_handle_run  # type: ignore
        self._orig_lock = None
        self._orig_handle_run = None

    def __enter__(self) -> "LockOrderSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------ recording
    @staticmethod
    def _creation_site() -> str:
        """file:line of the threading.Lock() caller — skipping any frames
        from THIS module, so stacked sanitizers (suite-wide autouse plus
        an explicit fixture: the inner factory calls the outer factory)
        still attribute every lock to its real creation site instead of
        collapsing all locks onto one make_lock line (which would zero
        out edge recording — edges require distinct sites)."""
        import sys

        frame = sys._getframe(1)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:  # pragma: no cover - interpreter-internal caller
            return "<unknown>:0"
        # last TWO path components: two files sharing a basename AND a
        # line number must not collapse into one graph node (a collision
        # could weld unrelated locks together and report a false cycle)
        tail = "/".join(frame.f_code.co_filename.rsplit("/", 2)[-2:])
        return f"{tail}:{frame.f_lineno}"

    def _held(self) -> list:
        stack = getattr(self._tls, "held", None)
        if stack is None:
            stack = self._tls.held = []
        return stack

    def _loop_running_here(self) -> bool:
        try:
            asyncio.get_running_loop()
            return True
        except RuntimeError:
            return False

    def _note_acquire(self, lock: _SanitizedLock) -> None:
        held = self._held()
        # Purge hand-off residue: a lock acquired HERE but released on
        # another thread keeps its stack entry (the releasing thread's
        # _note_release can't see this stack). Its _holder is by then
        # None or another thread — treating it as still-held would record
        # phantom edges and manufacture false cycles.
        me = threading.get_ident()
        if held:
            held[:] = [e for e in held if e[0]._holder == me]
        tick = (
            getattr(self._tls, "loop_ticks", 0)
            if self._loop_running_here() else None
        )
        new_edges = [
            (h.site, lock.site) for h, _t in held if h.site != lock.site
        ]
        held.append((lock, tick))
        if not new_edges:
            return
        with self._meta:
            for a, b in new_edges:
                if b in self.edges.setdefault(a, set()):
                    continue
                self.edges[a].add(b)
                self.edge_where[(a, b)] = threading.current_thread().name
                cycle = self._find_path(b, a)
                if cycle is not None:
                    self.violations.append(
                        "lock-order cycle: "
                        + " -> ".join([a] + cycle)
                        + f" (edge {a} -> {b} closed the cycle on thread "
                        f"{threading.current_thread().name}; a cross-thread "
                        f"interleaving of these acquisitions deadlocks)"
                    )

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> ... -> dst in the edge graph (caller holds
        _meta). Returns the node list after src, or None."""
        seen = set()
        stack: list[tuple[str, list[str]]] = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def _note_release(self, lock: _SanitizedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _l, tick = held.pop(i)
                if tick is not None:
                    now = getattr(self._tls, "loop_ticks", 0)
                    if now != tick:
                        with self._meta:
                            self.violations.append(
                                f"lock {lock.site} was held across an "
                                f"event-loop hop ({now - tick} other "
                                f"callback(s) ran on the loop while it was "
                                f"held) — a threading lock in a coroutine "
                                f"must not span an await"
                            )
                return
        # release of a lock acquired before install (or on another
        # thread's stack for hand-off patterns): not ours to judge

    # ------------------------------------------------------------ reporting
    def assert_clean(self) -> None:
        if self.violations:
            raise LockOrderViolation(
                f"{len(self.violations)} lock-discipline violation(s):\n"
                + "\n".join(f"  - {v}" for v in self.violations)
            )


def fixture_pods(scheduler_name: str = SCHEDULER_NAME) -> list[RawPod]:
    """The reference's 3 graduated nginx test pods (ai-test-pods.yaml)."""
    shapes = [
        ("ai-test-pod-1", "100m", "128Mi"),
        ("ai-test-pod-2", "250m", "256Mi"),
        ("ai-test-pod-3", "500m", "512Mi"),
    ]
    return [
        RawPod(
            name=name,
            namespace="default",
            scheduler_name=scheduler_name,
            container_requests=({"cpu": cpu, "memory": mem},),
        )
        for name, cpu, mem in shapes
    ]


def synthetic_cluster(
    n_nodes: int = 3,
    cpu_cores: float = 16.0,
    memory_gb: float = 64.0,
    max_pods: int = 110,
    load_spread: bool = True,
) -> FakeCluster:
    """A FakeCluster with n nodes at varied synthetic load levels."""
    cluster = FakeCluster()
    for i in range(n_nodes):
        load = (i * 37 % 90) if load_spread else None
        cluster.add_node(
            FakeNode(
                name=f"node-{i}",
                cpu_capacity_cores=cpu_cores,
                memory_capacity_gb=memory_gb,
                max_pods=max_pods,
                cpu_usage_percent=float(load) if load is not None else None,
                memory_usage_percent=float(load) if load is not None else None,
                labels={"zone": f"z{i % 4}"},
            )
        )
    return cluster


def pod_burst(
    n_pods: int,
    scheduler_name: str = SCHEDULER_NAME,
    distinct_shapes: int = 8,
) -> list[RawPod]:
    """A burst of pending pods with `distinct_shapes` resource shapes.

    distinct_shapes controls the decision-cache hit rate: a 1000-pod burst
    with 8 shapes means ~992 decisions are cache-servable, which mirrors real
    bursts (replicas of few deployments) and the reference's cache-key
    equivalence design (scheduler.py:265-271).
    """
    pods = []
    for i in range(n_pods):
        shape = i % distinct_shapes
        cpu_m = 100 + 50 * shape
        mem_mi = 128 * (1 + shape % 4)
        pods.append(
            RawPod(
                name=f"burst-pod-{i}",
                namespace="default",
                scheduler_name=scheduler_name,
                container_requests=({"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"},),
                priority=shape % 3,
            )
        )
    return pods
