"""Fixture generators shared by tests and bench.py.

The reference ships 3 nginx fixture pods with graduated requests
(reference ai-test-pods.yaml:1-44: 100m/128Mi, 250m/256Mi, 500m/512Mi)
targeting schedulerName ai-llama-scheduler. `fixture_pods()` reproduces that
workload; `synthetic_cluster`/`pod_burst` generate the BASELINE stress shapes
(64/256-node clusters, 1000-pod bursts).

Also home of `async_deadline()` — the Python-3.10-compatible stand-in for
the 3.11+ ``asyncio.timeout`` context manager that every async test's
watchdog goes through (the package floor is >=3.10; tools/py310_lint.py
keeps direct 3.11+-only calls from creeping back in).
"""

from __future__ import annotations

import asyncio

from k8s_llm_scheduler_tpu.cluster.fake import FakeCluster, FakeNode
from k8s_llm_scheduler_tpu.cluster.interface import RawPod

SCHEDULER_NAME = "ai-llama-scheduler"


class _Py310Deadline:
    """Minimal backport of the 3.11 timeout context manager: arm a timer
    that cancels the CURRENT task; translate the resulting CancelledError
    into TimeoutError iff this deadline (not an outer cancel) fired."""

    def __init__(self, seconds: float) -> None:
        self._seconds = seconds
        self._fired = False
        self._handle = None
        self._task = None

    async def __aenter__(self) -> "_Py310Deadline":
        self._task = asyncio.current_task()
        loop = asyncio.get_running_loop()
        self._handle = loop.call_later(self._seconds, self._on_timeout)
        return self

    def _on_timeout(self) -> None:
        self._fired = True
        if self._task is not None:
            self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if self._handle is not None:
            self._handle.cancel()
        if self._fired:
            if exc_type is asyncio.CancelledError:
                raise TimeoutError(
                    f"deadline of {self._seconds}s expired"
                ) from exc
            if exc_type is None:
                # Timer fired in the gap between the block's last await and
                # exit: the task.cancel() is still pending and would escape
                # as a bare CancelledError at the caller's NEXT await.
                # Absorb it at a checkpoint here and report the expiry
                # (3.11's native timeout resolves this boundary the same
                # way, via Task.uncancel bookkeeping).
                try:
                    await asyncio.sleep(0)
                except asyncio.CancelledError:
                    raise TimeoutError(
                        f"deadline of {self._seconds}s expired"
                    ) from None
        return False


def async_deadline(seconds: float):
    """``async with async_deadline(30): ...`` — bound an async block's wall
    time. Python 3.11+'s native scoped timeout when available (it handles
    nested-cancellation bookkeeping via Task.uncancel); a call_later-based
    shim with the same raise-TimeoutError contract on 3.10."""
    native = getattr(asyncio, "timeout", None)  # 3.11+
    if native is not None:
        return native(seconds)
    return _Py310Deadline(seconds)


def fixture_pods(scheduler_name: str = SCHEDULER_NAME) -> list[RawPod]:
    """The reference's 3 graduated nginx test pods (ai-test-pods.yaml)."""
    shapes = [
        ("ai-test-pod-1", "100m", "128Mi"),
        ("ai-test-pod-2", "250m", "256Mi"),
        ("ai-test-pod-3", "500m", "512Mi"),
    ]
    return [
        RawPod(
            name=name,
            namespace="default",
            scheduler_name=scheduler_name,
            container_requests=({"cpu": cpu, "memory": mem},),
        )
        for name, cpu, mem in shapes
    ]


def synthetic_cluster(
    n_nodes: int = 3,
    cpu_cores: float = 16.0,
    memory_gb: float = 64.0,
    max_pods: int = 110,
    load_spread: bool = True,
) -> FakeCluster:
    """A FakeCluster with n nodes at varied synthetic load levels."""
    cluster = FakeCluster()
    for i in range(n_nodes):
        load = (i * 37 % 90) if load_spread else None
        cluster.add_node(
            FakeNode(
                name=f"node-{i}",
                cpu_capacity_cores=cpu_cores,
                memory_capacity_gb=memory_gb,
                max_pods=max_pods,
                cpu_usage_percent=float(load) if load is not None else None,
                memory_usage_percent=float(load) if load is not None else None,
                labels={"zone": f"z{i % 4}"},
            )
        )
    return cluster


def pod_burst(
    n_pods: int,
    scheduler_name: str = SCHEDULER_NAME,
    distinct_shapes: int = 8,
) -> list[RawPod]:
    """A burst of pending pods with `distinct_shapes` resource shapes.

    distinct_shapes controls the decision-cache hit rate: a 1000-pod burst
    with 8 shapes means ~992 decisions are cache-servable, which mirrors real
    bursts (replicas of few deployments) and the reference's cache-key
    equivalence design (scheduler.py:265-271).
    """
    pods = []
    for i in range(n_pods):
        shape = i % distinct_shapes
        cpu_m = 100 + 50 * shape
        mem_mi = 128 * (1 + shape % 4)
        pods.append(
            RawPod(
                name=f"burst-pod-{i}",
                namespace="default",
                scheduler_name=scheduler_name,
                container_requests=({"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"},),
                priority=shape % 3,
            )
        )
    return pods
