"""Training/fine-tuning support: sharded causal-LM train step."""
