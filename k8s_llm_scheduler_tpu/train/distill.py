"""Fine-tune the decision model on scheduler decisions (self-distillation).

The reference consumes a frozen hosted model; there is no way to improve
its decisions from operational experience. This module closes that loop:
generate (cluster-state prompt -> decision JSON) pairs — from the heuristic
fallback scorer as a bootstrap teacher, or in production from logged
(prompt, accepted placement) records — and train the in-tree decision
model on them with the sharded train step (train/train_step.py), saving an
orbax checkpoint that `build_local_backend(checkpoint_path=...)` serves
directly.

Surface: `python -m k8s_llm_scheduler_tpu.cli train --steps N --out DIR`.
"""

from __future__ import annotations

import json
import logging
from typing import Iterator

import numpy as np

from k8s_llm_scheduler_tpu.core.fallback import fallback_decision
from k8s_llm_scheduler_tpu.core.prompt import PromptEngine
from k8s_llm_scheduler_tpu.engine.tokenizer import Tokenizer

logger = logging.getLogger(__name__)


def random_cases(n_nodes: int = 5, seed: int = 0):
    """Endless randomized (pod, nodes) scheduling cases — THE training
    distribution. train/eval.py draws its held-out cases from this same
    generator at a disjoint seed, so agreement measured there stays
    on-distribution by construction when this is tuned."""
    import dataclasses

    from k8s_llm_scheduler_tpu.cluster.interface import raw_pod_to_spec
    from k8s_llm_scheduler_tpu.testing import pod_burst, synthetic_cluster

    rng = np.random.default_rng(seed)
    while True:
        cluster = synthetic_cluster(int(rng.integers(2, n_nodes + 1)))
        base_nodes = cluster.get_node_metrics()
        cluster.close()
        # synthetic_cluster's load levels are deterministic — without this
        # perturbation the corpus collapses to ~16 distinct sequences
        nodes = [
            dataclasses.replace(
                n,
                cpu_usage_percent=float(rng.uniform(5, 95)),
                memory_usage_percent=float(rng.uniform(5, 95)),
                pod_count=int(rng.integers(0, n.max_pods // 2)),
            )
            for n in base_nodes
        ]
        for raw in pod_burst(4, distinct_shapes=4):
            pod = raw_pod_to_spec(raw)
            yield (
                dataclasses.replace(
                    pod,
                    cpu_request=round(float(rng.uniform(0.05, 2.0)), 3),
                    memory_request=round(float(rng.uniform(0.064, 2.0)), 3),
                ),
                nodes,
            )


def teacher_pairs(
    tokenizer: Tokenizer,
    n_nodes: int = 5,
    seed: int = 0,
) -> Iterator[tuple[list[int], int]]:
    """Endless (prompt + decision tokens, answer_start) samples from the
    heuristic teacher over randomized synthetic clusters.

    Each sample is the full chat prompt (system + cluster state + pod)
    followed by the teacher's decision JSON and EOS — exactly the
    sequence the serving path decodes. `answer_start` is the index of the
    first decision token: the loss masks to the answer span
    (train_step.causal_lm_loss loss_start), because a ~60-token answer
    behind a ~1.5k-token prompt otherwise contributes ~4% of the gradient
    and the decision head stays near uniform for hundreds of steps.
    """
    pe = PromptEngine()
    for pod, nodes in random_cases(n_nodes=n_nodes, seed=seed):
        decision = fallback_decision(
            nodes, reason="teacher", strategy="resource_balanced", pod=pod
        )
        if decision is None:
            continue
        cluster_part, pod_part = pe.split_prompt(pod, nodes)
        prompt = tokenizer.chat_prompt(
            pe.system_prompt, cluster_part + pod_part
        )
        answer = json.dumps(
            {
                "selected_node": decision.selected_node,
                "confidence": round(decision.confidence, 2),
                "reasoning": "resource balanced",
            }
        )
        yield (
            prompt + tokenizer.encode(answer) + [tokenizer.eos_id],
            len(prompt),
        )


def make_batches(
    tokenizer: Tokenizer,
    batch_size: int,
    seq_len: int,
    n_nodes: int = 5,
    seed: int = 0,
) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Batched, padded (tokens, seq_lens, answer_starts) for the train
    step (answer_starts feeds the loss mask)."""
    pairs = teacher_pairs(tokenizer, n_nodes=n_nodes, seed=seed)
    pad = tokenizer.pad_id
    warned = False
    while True:
        tokens = np.full((batch_size, seq_len), pad, dtype=np.int32)
        lens = np.zeros(batch_size, dtype=np.int32)
        starts = np.zeros(batch_size, dtype=np.int32)
        for b in range(batch_size):
            ids, ans_start = next(pairs)
            if len(ids) > seq_len:
                # Truncate from the LEFT: the decision JSON lives at the
                # tail, and a distillation batch that drops the answer
                # trains on prompt text only (silently learning nothing).
                cut = len(ids) - seq_len
                ids = ids[-seq_len:]
                ans_start = max(0, ans_start - cut)
                if not warned:
                    logger.warning(
                        "teacher pairs exceed seq_len=%d; truncating prompt "
                        "context from the left (answers preserved)", seq_len,
                    )
                    warned = True
            tokens[b, : len(ids)] = ids
            lens[b] = len(ids)
            starts[b] = ans_start
        yield tokens, lens, starts


def train_and_save(
    cfg,
    out_dir: str,
    steps: int = 20,
    batch_size: int = 4,
    seq_len: int = 2048,
    mesh_axes: dict[str, int] | None = None,
    log_every: int = 5,
    seed: int = 0,
    lr: float = 3e-4,
) -> float:
    """Run `steps` of answer-masked fine-tuning on teacher pairs and save
    an orbax checkpoint servable via checkpoint_path. Returns the final
    loss. `lr` defaults suit bootstrap distillation of the small configs
    from random init (the 1e-5 fine-tune default under-trained them by
    orders of magnitude)."""
    import jax
    import optax

    from k8s_llm_scheduler_tpu.engine.tokenizer import ByteTokenizer
    from k8s_llm_scheduler_tpu.models.loader import save_checkpoint
    from k8s_llm_scheduler_tpu.parallel.mesh import mesh_from_config
    from k8s_llm_scheduler_tpu.train.train_step import make_train_step

    tokenizer = ByteTokenizer(vocab_size=max(512, cfg.vocab_size))
    if jax.process_count() > 1:
        # Multi-host: dp/fsdp span processes (DCN), tp/sp stay within one
        # host (ICI) — mesh_from_config's flat device slice is process-
        # location-blind and would scatter tp across hosts.
        from k8s_llm_scheduler_tpu.parallel.distributed import multihost_mesh

        axes = dict(mesh_axes or {})
        mesh = multihost_mesh(
            {k: v for k, v in axes.items() if k in ("dp", "fsdp")},
            {k: v for k, v in axes.items() if k in ("tp", "sp")} or {"tp": 1},
        )
    else:
        mesh = mesh_from_config(mesh_axes)
    init_fn, step_fn = make_train_step(
        cfg, mesh, optimizer=optax.adamw(lr)
    )
    state = init_fn(jax.random.PRNGKey(seed))
    batches = make_batches(tokenizer, batch_size, seq_len, seed=seed)
    loss = float("nan")
    for step in range(1, steps + 1):
        tokens, lens, starts = next(batches)
        tokens, lens, starts = step_fn.place_batch(tokens, lens, starts)
        state, loss_arr = step_fn(state, tokens, lens, starts)
        if step % log_every == 0 or step == steps:
            loss = float(loss_arr)
            logger.info("step %d/%d loss %.4f", step, steps, loss)
    if jax.process_index() == 0:
        # coordinator-only side effect; worker hosts hold the same
        # (replicated-spec) state and must not race the directory write
        save_checkpoint(out_dir, state.params)
        logger.info("checkpoint saved to %s", out_dir)
    return loss
